"""Request-resilience layer tests: deadlines, retry/backoff, fault injection.

The reproduction of the reference's resilience test surface
(SearchWithRandomExceptionsTests / MockTransportService chaos +
TimeLimitingCollector semantics): searches under injected network faults must
degrade to accurate partial responses — 200 with honest `_shards` accounting —
and the write path must never silently drop a replica op.

Everything here is deterministic: faults come from seeded FaultPolicy rules,
backoff schedules from seeded RNGs, and "slow" is an injected transport delay,
never a handler sleep racing the wall clock.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from elasticsearch_tpu.common.deadline import NO_DEADLINE, Deadline, parse_timevalue
from elasticsearch_tpu.common.errors import (
    NodeNotConnectedError,
    ReceiveTimeoutError,
    TransportError,
    VersionConflictError,
)
from elasticsearch_tpu.common.retry import (
    RetryExhaustedError,
    RetryPolicy,
    is_transient,
)
from elasticsearch_tpu.transport.faults import FaultPolicy, FaultRule
from elasticsearch_tpu.transport.local import LocalTransport, LocalTransportRegistry
from elasticsearch_tpu.transport.service import TransportService

from .harness import TestCluster

pytestmark = pytest.mark.resilience

A_QUERY = "indices:data/read/search[phase/query]"


# ---------------------------------------------------------------------------
# Deadline / timevalue units
# ---------------------------------------------------------------------------


def test_parse_timevalue_units():
    assert parse_timevalue("50ms") == pytest.approx(0.05)
    assert parse_timevalue("2s") == pytest.approx(2.0)
    assert parse_timevalue("1m") == pytest.approx(60.0)
    assert parse_timevalue("1h") == pytest.approx(3600.0)
    # bare numbers are MILLISECONDS (reference TimeValue default)
    assert parse_timevalue(500) == pytest.approx(0.5)
    assert parse_timevalue("250") == pytest.approx(0.25)
    # no budget: None, and the reference's -1 sentinel
    assert parse_timevalue(None) is None
    assert parse_timevalue(-1) is None
    assert parse_timevalue("-1") is None
    with pytest.raises(ValueError):
        parse_timevalue("fast-ish")


def test_deadline_budget_and_clamp():
    d = Deadline.after(10.0)
    assert d.bounded and not d.expired()
    assert 9.0 < d.remaining() <= 10.0
    # clamp takes the tighter of the two
    assert d.clamp(5.0) == pytest.approx(5.0, abs=0.1)
    assert d.clamp(60.0) == pytest.approx(d.remaining(), abs=0.1)
    assert d.clamp(None) == pytest.approx(d.remaining(), abs=0.1)


def test_deadline_expiry():
    d = Deadline.after(0.0)
    assert d.expired()
    assert d.remaining() == 0.0
    assert d.clamp(30.0) == 0.0


def test_unbounded_deadline_is_inert():
    d = Deadline.after(None)
    assert not d.bounded and not d.expired()
    assert d.remaining() is None
    assert d.clamp(30.0) == 30.0
    assert d.clamp(None) is None
    assert NO_DEADLINE.clamp(7.0) == 7.0


# ---------------------------------------------------------------------------
# RetryPolicy: jitter bounds, classification, deadline budget
# ---------------------------------------------------------------------------


def test_backoff_jitter_bounds():
    """Decorrelated jitter: every sleep lands in [base, cap] and never exceeds
    3x the previous sleep."""
    policy = RetryPolicy(max_attempts=10, base_s=0.05, cap_s=2.0,
                         rng=random.Random(7))
    prev = None
    for _ in range(200):
        nxt = policy.next_backoff(prev)
        assert policy.base_s <= nxt <= policy.cap_s
        assert nxt <= max(policy.base_s, (prev if prev is not None
                                          else policy.base_s) * 3.0) + 1e-9
        prev = nxt


def test_backoff_is_seeded_deterministic():
    a = RetryPolicy(rng=random.Random(13))
    b = RetryPolicy(rng=random.Random(13))
    sa = sb = None
    for _ in range(20):
        sa, sb = a.next_backoff(sa), b.next_backoff(sb)
        assert sa == sb


def test_retry_transient_then_success_counts_attempts():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransportError("blip")
        return "ok"

    policy = RetryPolicy(max_attempts=5, rng=random.Random(0), sleep=lambda s: None)
    assert policy.call(flaky) == "ok"
    assert len(calls) == 3


def test_no_retry_on_non_transient():
    calls = []

    def conflict():
        calls.append(1)
        raise VersionConflictError("d#1", 2, 1)

    policy = RetryPolicy(max_attempts=5, rng=random.Random(0), sleep=lambda s: None)
    with pytest.raises(VersionConflictError):
        policy.call(conflict)
    assert len(calls) == 1  # deterministic failures never retry


def test_retry_exhaustion_carries_cause():
    policy = RetryPolicy(max_attempts=3, rng=random.Random(0), sleep=lambda s: None)
    with pytest.raises(RetryExhaustedError) as ei:
        policy.call(lambda: (_ for _ in ()).throw(NodeNotConnectedError("gone")))
    assert ei.value.attempts == 3
    assert isinstance(ei.value.cause, NodeNotConnectedError)


def test_retry_deadline_exhaustion_stops_early():
    """A sleep that would eat the whole remaining budget is not taken — the
    policy reports exhaustion instead of sleeping past the deadline."""
    slept = []

    def sleeping(s):
        slept.append(s)
        time.sleep(s)

    policy = RetryPolicy(max_attempts=50, base_s=0.1, cap_s=0.1,
                         rng=random.Random(0), sleep=sleeping)
    deadline = Deadline.after(0.25)
    calls = []

    def always_down():
        calls.append(1)
        raise TransportError("down")

    with pytest.raises(RetryExhaustedError):
        policy.call(always_down, deadline=deadline)
    assert len(calls) <= 4  # nowhere near the attempt cap — budget won
    assert sum(slept) <= 0.25 + 1e-6


def test_is_transient_classification():
    assert is_transient(NodeNotConnectedError("x"))
    assert is_transient(ReceiveTimeoutError("x"))
    assert is_transient(TransportError("x"))
    assert not is_transient(VersionConflictError("d#1", 2, 1))
    from elasticsearch_tpu.common.errors import ActionNotFoundError
    assert not is_transient(ActionNotFoundError("no handler"))


# ---------------------------------------------------------------------------
# FaultPolicy over a live transport pair
# ---------------------------------------------------------------------------


@pytest.fixture()
def local_pair():
    registry = LocalTransportRegistry()
    a = TransportService(LocalTransport("a:1", registry))
    b = TransportService(LocalTransport("b:1", registry))
    b.register_handler("t/echo", lambda req, ch: {"v": req.get("v")})
    yield a, b
    a.close()
    b.close()


def test_fault_error_and_disconnect_rules(local_pair):
    a, b = local_pair
    policy = FaultPolicy(seed=1).install(a)
    policy.error(TransportError("injected"), action="t/echo", max_hits=1)
    with pytest.raises(TransportError, match="injected"):
        a.submit_request("b:1", "t/echo", {"v": 1}, timeout=5)
    policy.disconnect(action="t/echo", max_hits=1)
    with pytest.raises(NodeNotConnectedError):
        a.submit_request("b:1", "t/echo", {"v": 2}, timeout=5)
    # both rules disarmed: the path heals
    assert a.submit_request("b:1", "t/echo", {"v": 3}, timeout=5) == {"v": 3}
    assert policy.injected == 2


def test_fault_drop_surfaces_as_response_timeout(local_pair):
    a, b = local_pair
    FaultPolicy(seed=1).install(a)
    a.fault_policy.drop(action="t/echo", max_hits=1)
    with pytest.raises(ReceiveTimeoutError):
        a.submit_request("b:1", "t/echo", {"v": 1}, timeout=0.2)
    assert a.submit_request("b:1", "t/echo", {"v": 2}, timeout=5) == {"v": 2}


def test_fault_delay_rule_delays_but_delivers(local_pair):
    a, b = local_pair
    FaultPolicy(seed=1).install(a)
    a.fault_policy.delay(0.15, action="t/echo", max_hits=1)
    t0 = time.monotonic()
    assert a.submit_request("b:1", "t/echo", {"v": 9}, timeout=5) == {"v": 9}
    assert time.monotonic() - t0 >= 0.15


def test_recv_rule_matches_receiver_address(local_pair):
    """direction="recv" rules match the RECEIVING node's own address — a node
    pattern must select the faulted receiver, not silently never fire."""
    a, b = local_pair
    FaultPolicy(seed=1).install(b)
    b.fault_policy.error(TransportError("recv-injected"), action="t/echo",
                         node="b:1", direction="recv")
    with pytest.raises(TransportError, match="recv-injected"):
        a.submit_request("b:1", "t/echo", {"v": 1}, timeout=5)
    # a rule for some OTHER receiver stays dormant
    b.fault_policy.clear()
    b.fault_policy.error(TransportError("wrong node"), action="t/echo",
                         node="z:9", direction="recv")
    assert a.submit_request("b:1", "t/echo", {"v": 2}, timeout=5) == {"v": 2}


def test_fault_rule_node_and_where_matching(local_pair):
    a, b = local_pair
    policy = FaultPolicy(seed=1).install(a)
    # node pattern that matches nothing we send to
    policy.disconnect(action="t/echo", node="z:*")
    # where-refinement: only requests for shard 0
    policy.error(TransportError("shard0 only"), action="t/echo",
                 where=lambda act, addr, req: (req or {}).get("shard") == 0)
    assert a.submit_request("b:1", "t/echo", {"v": 1, "shard": 1}, timeout=5) \
        == {"v": 1}
    with pytest.raises(TransportError, match="shard0 only"):
        a.submit_request("b:1", "t/echo", {"v": 1, "shard": 0}, timeout=5)


def test_fault_probability_replays_from_seed():
    decisions = []
    for _ in range(2):
        policy = FaultPolicy(seed=42)
        policy.error(probability=0.5, action="t/*")
        decisions.append([policy.decide("t/echo", "n:1", {}) is not None
                          for _ in range(64)])
    assert decisions[0] == decisions[1]
    assert any(decisions[0]) and not all(decisions[0])


def test_fault_rule_rejects_unknown_kind():
    with pytest.raises(ValueError):
        FaultRule(kind="explode")
    with pytest.raises(ValueError):
        FaultRule(direction="sideways")


# ---------------------------------------------------------------------------
# cluster: search under injected faults (the acceptance scenarios)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def two_node_cluster(tmp_path_factory):
    with TestCluster(n_nodes=2, data_root=tmp_path_factory.mktemp("resil"),
                     seed=11, name="rs") as cluster:
        # pin the transport scatter-gather path: the mesh SPMD bypass serves
        # co-located copies without any RPC, which would dodge injected faults
        for node in cluster.nodes.values():
            node.actions.mesh_serving.enabled = False
        client = cluster.client()
        client.create_index("resil", {"settings": {
            "index.number_of_shards": 2, "index.number_of_replicas": 1}})
        cluster.ensure_green("resil")
        for i in range(40):
            client.index("resil", "doc", {"title": f"hello world {i}", "n": i},
                         id=str(i))
        client.refresh("resil")
        yield cluster


def _search_node(cluster):
    name = sorted(cluster.nodes)[0]
    return name, cluster.nodes[name]


def test_search_fails_over_around_disconnect_faults(two_node_cluster):
    """(a1) one copy of every group downed via disconnect rules: failover to
    the other copy keeps the search whole — 200, zero failed shards."""
    cluster = two_node_cluster
    name, node = _search_node(cluster)
    other = next(n for n in sorted(cluster.nodes) if n != name)
    policy = cluster.fault_policy(name, seed=3)
    try:
        rule = policy.disconnect(action=A_QUERY, node=cluster.address(other))
        # _prefer_node pins the REMOTE (faulted) copy as every chain's first
        # candidate, so the test cannot vacuously pass by local-only routing
        resp = node.client().search(
            "resil", {"query": {"match": {"title": "hello"}}},
            preference=f"_prefer_node:{cluster.nodes[other].local_node.id}")
        assert resp["hits"]["total"] == 40
        assert resp["_shards"]["failed"] == 0
        assert resp["_shards"]["successful"] == resp["_shards"]["total"]
        assert resp["timed_out"] is False
        # every shard group's first attempt hit the downed copy; failover is
        # what kept failed == 0
        assert rule.hits >= 2, rule.hits
    finally:
        cluster.clear_faults()


def test_search_reports_failure_per_downed_copy(two_node_cluster):
    """(a2) EVERY copy of every group downed: chains exhaust — still 200, with
    _shards.failed == number of exhausted chains and a failure entry naming
    each downed copy."""
    cluster = two_node_cluster
    name, node = _search_node(cluster)
    policy = cluster.fault_policy(name, seed=4)
    try:
        policy.disconnect(action=A_QUERY)  # all copies, all nodes
        resp = node.client().search("resil", {"query": {"match": {"title": "hello"}}})
        assert resp["hits"]["total"] == 0
        assert resp["hits"]["hits"] == []
        assert resp["_shards"]["failed"] == resp["_shards"]["total"] == 2
        assert resp["_shards"]["successful"] == 0
        failures = resp["_shards"]["failures"]
        # 2 groups x 2 copies — one entry per downed copy, naming its node
        assert len(failures) == 4
        assert all(f.get("node") for f in failures)
        per_shard = {f["shard"] for f in failures}
        assert per_shard == {0, 1}
    finally:
        cluster.clear_faults()


def test_timeout_against_delayed_shard_returns_partial(two_node_cluster):
    """(b) `timeout=50ms` with one shard's transport delay-faulted: the
    response arrives promptly, timed_out, with the healthy shard's hits."""
    cluster = two_node_cluster
    name, node = _search_node(cluster)
    body = {"query": {"match": {"title": "hello"}}, "size": 40}
    # warm the exact query first (device compile happens once per plan shape):
    # the budget below must race the injected TRANSPORT delay, not a cold jit
    warm = node.client().search("resil", body)
    assert warm["hits"]["total"] == 40
    policy = cluster.fault_policy(name, seed=5)
    try:
        policy.delay(0.6, action=A_QUERY,
                     where=lambda act, addr, req: (req or {}).get("shard") == 0)
        t0 = time.monotonic()
        resp = node.client().search("resil", {**body, "timeout": "150ms"})
        took = time.monotonic() - t0
        assert resp["timed_out"] is True
        # partial: shard 1 answered, shard 0's chain ran out of budget
        assert 0 < resp["hits"]["total"] < 40
        assert len(resp["hits"]["hits"]) == resp["hits"]["total"]
        assert resp["_shards"]["failed"] >= 1
        assert any(f["shard"] == 0 for f in resp["_shards"]["failures"])
        # the whole point: no 60s attempt timeout, no stacked waits
        assert took < 6.0
    finally:
        cluster.clear_faults()


def test_search_timeout_via_rest_query_param(two_node_cluster):
    """REST `?timeout=` reaches ParsedSearchRequest.timeout_s and an untroubled
    search completes well inside it, timed_out false."""
    cluster = two_node_cluster
    _name, node = _search_node(cluster)
    from elasticsearch_tpu.rest import RestRequest, build_rest_controller

    rc = build_rest_controller(node)
    resp = rc.dispatch(RestRequest(method="GET", path="/resil/_search",
                                   params={"timeout": "30s", "size": "5"}))
    assert resp.status == 200
    assert resp.body["timed_out"] is False
    assert resp.body["hits"]["total"] == 40
    assert len(resp.body["hits"]["hits"]) == 5
    # a malformed timeout is a parse error (400), not a 500
    bad = rc.dispatch(RestRequest(method="GET", path="/resil/_search",
                                  params={"timeout": "soonish"}))
    assert bad.status == 400


# ---------------------------------------------------------------------------
# shard-side deadline: segment-granularity partial results
# ---------------------------------------------------------------------------


def test_query_phase_expired_deadline_returns_empty_partial(two_node_cluster):
    cluster = two_node_cluster
    _name, node = _search_node(cluster)
    from elasticsearch_tpu.search.service import execute_query_phase, parse_search_body

    shard_id, ctx = _any_local_shard_ctx(node, "resil")
    req = parse_search_body({"query": {"match": {"title": "hello"}},
                             "sort": [{"n": "asc"}]})
    r = execute_query_phase(ctx, req, shard_id=shard_id,
                            deadline=Deadline.after(0.0))
    assert r.timed_out is True
    assert r.docs == [] and r.total == 0


def test_query_phase_generous_deadline_is_complete(two_node_cluster):
    cluster = two_node_cluster
    _name, node = _search_node(cluster)
    from elasticsearch_tpu.search.service import execute_query_phase, parse_search_body

    shard_id, ctx = _any_local_shard_ctx(node, "resil")
    req = parse_search_body({"query": {"match": {"title": "hello"}},
                             "sort": [{"n": "asc"}], "size": 40})
    full = execute_query_phase(ctx, req, shard_id=shard_id)
    bounded = execute_query_phase(ctx, req, shard_id=shard_id,
                                  deadline=Deadline.after(30.0))
    assert bounded.timed_out is False
    assert bounded.total == full.total
    assert [d[1] for d in bounded.docs] == [d[1] for d in full.docs]


def _any_local_shard_ctx(node, index):
    svc = node.indices.index_service(index)
    shard_id = sorted(svc.shards)[0]
    return shard_id, node.actions._shard_ctx(index, shard_id)


# ---------------------------------------------------------------------------
# write path: no replica failure is silently swallowed
# ---------------------------------------------------------------------------


def test_dead_replica_is_reported_shard_failed(tmp_path):
    """Regression for the bare `except SearchEngineError: pass` replica loops:
    with the replica's write transport hard-down (disconnect faults on the
    primary node's sender), a bulk must still succeed on the primary AND the
    master must mark the replica copy failed — not leave it silently
    diverging until the next recovery."""
    with TestCluster(n_nodes=2, data_root=tmp_path, seed=21, name="rw") as cluster:
        client = cluster.client()
        client.create_index("wr", {"settings": {
            "index.number_of_shards": 1, "index.number_of_replicas": 1}})
        cluster.ensure_green("wr")
        # find the primary's node; fault ALL replica-bound write traffic from it
        state = next(iter(cluster.nodes.values())).cluster_service.state
        primary = state.routing_table.index("wr").shard(0).primary
        primary_name = next(n for n, nd in cluster.nodes.items()
                            if nd.local_node.id == primary.node_id)
        primary_node = cluster.nodes[primary_name]
        # fast retry schedule so exhaustion happens in test time
        primary_node.actions.retry_policy = RetryPolicy(
            max_attempts=2, base_s=0.01, cap_s=0.02, rng=random.Random(0))
        policy = cluster.fault_policy(primary_name, seed=6)
        policy.disconnect(action="indices:data/write/*[r]")

        ops = [{"action": {"index": {"_index": "wr", "_type": "doc",
                                     "_id": str(i)}},
                "source": {"n": i}} for i in range(5)]
        resp = primary_node.client().bulk(ops)
        assert resp["errors"] is False  # primary writes all succeeded

        # the master must observe the replica copy failed (routed out of the
        # group) — poll briefly for the state update to land
        def replica_routed_out():
            st = primary_node.cluster_service.state
            group = st.routing_table.index("wr").shard(0)
            return all(not (r.active and r.node_id != primary.node_id)
                       for r in group.shards)

        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not replica_routed_out():
            time.sleep(0.05)
        assert replica_routed_out(), \
            primary_node.cluster_service.state.routing_table.index("wr").shard(0)


def test_single_doc_replica_failure_reported(tmp_path):
    """Same guarantee on the non-bulk path (_replicate): index one doc with the
    replica link down; the op acks and the copy is marked failed."""
    with TestCluster(n_nodes=2, data_root=tmp_path, seed=22, name="rx") as cluster:
        client = cluster.client()
        client.create_index("one", {"settings": {
            "index.number_of_shards": 1, "index.number_of_replicas": 1}})
        cluster.ensure_green("one")
        state = next(iter(cluster.nodes.values())).cluster_service.state
        primary = state.routing_table.index("one").shard(0).primary
        primary_name = next(n for n, nd in cluster.nodes.items()
                            if nd.local_node.id == primary.node_id)
        primary_node = cluster.nodes[primary_name]
        primary_node.actions.retry_policy = RetryPolicy(
            max_attempts=2, base_s=0.01, cap_s=0.02, rng=random.Random(0))
        cluster.fault_policy(primary_name, seed=7).disconnect(
            action="indices:data/write/*[r]")

        r = primary_node.client().index("one", "doc", {"v": 1}, id="1")
        assert r["_version"] == 1

        def replica_failed():
            st = primary_node.cluster_service.state
            group = st.routing_table.index("one").shard(0)
            return all(not (s.active and s.node_id != primary.node_id)
                       for s in group.shards)

        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not replica_failed():
            time.sleep(0.05)
        assert replica_failed()
