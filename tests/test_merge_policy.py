"""Tiered merge policy (index/merge_policy.py — ref: index/merge/policy/
TieredMergePolicyProvider.java) + engine merge integration."""

import numpy as np
import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index import Engine
from elasticsearch_tpu.index.merge_policy import TieredMergePolicy
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.search import ShardContext, parse_query, search_shard
from elasticsearch_tpu.search.similarity import SimilarityService


class FakeSeg:
    def __init__(self, size, docs=100, live=None):
        self._size = size
        self.doc_count = docs
        self._live = live if live is not None else docs

    def estimated_bytes(self):
        return self._size

    def live_count(self):
        return self._live


class TestPolicySelection:
    def test_under_budget_no_merge(self):
        p = TieredMergePolicy()
        segs = [FakeSeg(10 * 1024 ** 2) for _ in range(5)]
        assert p.find_merge(segs) is None

    def test_over_budget_merges_small_segments(self):
        p = TieredMergePolicy(Settings.from_flat(
            {"index.merge.policy.segments_per_tier": 4,
             "index.merge.policy.max_merge_at_once": 4}))
        # one big + many tiny: the tiny tail should be picked, not the big one
        segs = [FakeSeg(500 * 1024 ** 2)] + [FakeSeg(1024 ** 2) for _ in range(20)]
        spec = p.find_merge(segs)
        assert spec is not None
        assert spec.start >= 1  # excludes the big segment
        assert spec.end - spec.start <= 4

    def test_max_merged_segment_respected(self):
        p = TieredMergePolicy(Settings.from_flat(
            {"index.merge.policy.max_merged_segment_bytes": 10 * 1024 ** 2,
             "index.merge.policy.segments_per_tier": 2}))
        segs = [FakeSeg(8 * 1024 ** 2) for _ in range(6)]
        spec = p.find_merge(segs)
        # any window of 2+ segments exceeds 10MB → no legal merge
        assert spec is None

    def test_delete_heavy_segment_triggers_merge(self):
        p = TieredMergePolicy()
        # within budget, but one segment is 60% deleted
        segs = [FakeSeg(10 * 1024 ** 2, docs=100, live=100) for _ in range(3)]
        segs[1] = FakeSeg(10 * 1024 ** 2, docs=100, live=40)
        spec = p.find_merge(segs)
        assert spec is not None
        assert spec.start <= 1 < spec.end  # window covers the deleted-heavy segment

    def test_allowed_count_scales_with_tiers(self):
        p = TieredMergePolicy()
        small = [1024 ** 2] * 10
        big = [1024 ** 2] * 5 + [100 * 1024 ** 2] * 5
        assert p.allowed_segment_count(big) >= p.allowed_segment_count(small)


def build_engine(tmp_path, flat=None):
    settings = Settings.from_flat(flat or {})
    svc = MapperService(settings)
    e = Engine(str(tmp_path / "s"), svc, settings=settings)
    return e, svc


class TestEngineMerge:
    def test_maybe_merge_reduces_segment_count(self, tmp_path):
        e, svc = build_engine(tmp_path, {
            "index.merge.policy.segments_per_tier": 3,
            "index.merge.policy.max_merge_at_once": 5})
        for i in range(40):
            e.index("doc", str(i), {"body": f"word{i % 7} common text"})
            if i % 2 == 1:
                e.refresh()  # force many tiny segments
        before = len(e.acquire_searcher().segments)
        e.maybe_merge(max_merges=20)
        after = len(e.acquire_searcher().segments)
        assert after < before
        # all docs still searchable with correct count
        ctx = ShardContext(e.acquire_searcher(), svc,
                           SimilarityService(Settings.EMPTY, mapper_service=svc))
        td = search_shard(ctx, parse_query({"match": {"body": "common"}}), 50)
        assert len(td.hits) == 40

    def test_merge_preserves_get_and_versions(self, tmp_path):
        e, svc = build_engine(tmp_path, {
            "index.merge.policy.segments_per_tier": 2})
        for i in range(20):
            e.index("doc", str(i), {"n": i})
            e.refresh()
        e.index("doc", "5", {"n": 500})  # update → version 2
        e.delete("doc", "7")
        e.refresh()
        e.maybe_merge(max_merges=20)
        r = e.get("doc", "5")
        assert r.found and r.source["n"] == 500 and r.version == 2
        assert not e.get("doc", "7").found
        assert e.get("doc", "3").found

    def test_merge_then_flush_then_restart(self, tmp_path):
        e, svc = build_engine(tmp_path, {
            "index.merge.policy.segments_per_tier": 2})
        for i in range(12):
            e.index("doc", str(i), {"n": i})
            e.refresh()
        e.flush()
        e.maybe_merge(max_merges=10)  # merges persisted segments → new commit
        e.index("doc", "100", {"n": 100})  # translog-only doc
        e.translog.sync()
        e.close()
        e2 = Engine(str(tmp_path / "s"), svc, settings=Settings.EMPTY)
        e2.recover_from_store()
        e2.refresh()
        assert e2.get("doc", "100").found
        assert e2.get("doc", "3").found
        searcher = e2.acquire_searcher()
        assert searcher.live_doc_count() == 13

    def test_merge_with_buffered_docs_safe(self, tmp_path):
        """Docs sitting in the RAM buffer survive a concurrent merge (gen re-key)."""
        e, svc = build_engine(tmp_path, {
            "index.merge.policy.segments_per_tier": 2})
        for i in range(8):
            e.index("doc", str(i), {"n": i})
            e.refresh()
        e.index("doc", "buffered", {"n": 99})  # stays in buffer
        e.maybe_merge(max_merges=10)
        e.refresh()
        assert e.get("doc", "buffered").found
        assert e.acquire_searcher().live_doc_count() == 9


class TestIndexingMemoryController:
    def test_budget_forces_refresh_of_largest_buffers(self, tmp_path):
        """check_indexing_memory refreshes big buffers first when over budget
        (ref: IndexingMemoryController.java:52-85)."""
        from elasticsearch_tpu.node import Node
        from elasticsearch_tpu.transport.local import LocalTransportRegistry

        registry = LocalTransportRegistry()
        node = Node(name="imc_node", registry=registry,
                    settings={"index.refresh_interval": "-1"},
                    data_path=str(tmp_path / "n"))
        try:
            node.start([node.local_node.transport_address])
            node.wait_for_master()
            client = node.client()
            client.create_index("imc", {"settings": {"index.number_of_shards": 2}})
            client.cluster_health(wait_for_status="green", timeout=10)
            for i in range(50):
                client.index("imc", "doc", {"body": f"some text {i}" * 10}, id=str(i))
            shards = [s for svc in node.indices.indices.values()
                      for s in svc.shards.values()]
            buffered = sum(s.engine.indexing_buffer_bytes() for s in shards)
            assert buffered > 0
            # tiny budget → everything must be refreshed out
            n = node.indices.check_indexing_memory(budget_bytes=1)
            assert n >= 1
            assert sum(s.engine.indexing_buffer_bytes() for s in shards) == 0
            # under budget: no refreshes
            assert node.indices.check_indexing_memory(budget_bytes=1 << 30) == 0
        finally:
            node.close()
