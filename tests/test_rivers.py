"""Rivers: _river meta docs start/stop registered river types on the master.
ref: river/RiversService.java + river/dummy/DummyRiver.java."""

import time

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rivers import River
from elasticsearch_tpu.transport.local import LocalTransportRegistry


class CountingRiver(River):
    started = []
    closed = []

    def start(self):
        CountingRiver.started.append(self.name)
        # a pull-based river ingests through the normal client
        self.node.client().index("pulled", "doc",
                                 {"src": self.settings.get("source", "?")}, id="1")

    def close(self):
        CountingRiver.closed.append(self.name)


@pytest.fixture()
def node(tmp_path):
    n = Node(name="rv1", registry=LocalTransportRegistry(),
             settings={"rivers.check_interval": 600},  # drive reconcile manually
             data_path=str(tmp_path))
    n.start([n.local_node.transport_address])
    n.wait_for_master()
    n.rivers.types["counting"] = CountingRiver
    CountingRiver.started.clear()
    CountingRiver.closed.clear()
    yield n
    n.close()


class TestRivers:
    def test_meta_doc_starts_and_delete_closes(self, node):
        c = node.client()
        c.create_index("pulled", {"settings": {"number_of_shards": 1,
                                               "number_of_replicas": 0}})
        c.cluster_health(wait_for_status="green")
        c.index("_river", "myfeed", {"type": "counting", "source": "somewhere"},
                id="_meta", refresh=True)
        node.rivers.reconcile()
        assert CountingRiver.started == ["myfeed"]
        # the river ran: it pulled a doc through the client
        c.refresh("pulled")
        assert c.get("pulled", "doc", "1")["_source"]["src"] == "somewhere"
        # status doc written (ref: RiversService writes _status)
        st = c.get("_river", "myfeed", "_status")
        assert st["found"] and st["_source"]["status"] == "started"
        # idempotent: reconcile again doesn't double start
        node.rivers.reconcile()
        assert CountingRiver.started == ["myfeed"]
        # deleting the meta doc closes the river
        c.delete("_river", "myfeed", "_meta", refresh=True)
        node.rivers.reconcile()
        assert CountingRiver.closed == ["myfeed"]

    def test_unknown_type_is_skipped(self, node):
        c = node.client()
        c.index("_river", "bad", {"type": "no_such_type"}, id="_meta", refresh=True)
        node.rivers.reconcile()
        assert "bad" not in node.rivers.running

    def test_dummy_river_in_tree(self, node):
        c = node.client()
        c.index("_river", "d1", {"type": "dummy"}, id="_meta", refresh=True)
        node.rivers.reconcile()
        assert "d1" in node.rivers.running
