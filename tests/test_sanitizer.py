"""Runtime sanitizer (common/jaxenv.sanitize): the dynamic half of tpulint.

The load-bearing invariant: a WARMED single-shard query path neither
recompiles nor implicitly transfers — the second identical query must run
entirely from the executable cache under jax.transfer_guard("disallow").
This is the runtime proof behind the shape-bucketing design (ops/scoring
_compiled_cache, device_index._pow2_bucket) that tpulint TPU002 guards
statically.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticsearch_tpu.common.jaxenv import (
    CompileBudgetExceeded,
    SanitizerReport,
    sanitize,
)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index import Engine
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.search import ShardContext, parse_query, search_shard
from elasticsearch_tpu.search.similarity import SimilarityService

DOCS = [
    "the quick brown fox jumps over the lazy dog",
    "quick brown foxes leap over lazy dogs in summer",
    "the red fox and the brown bear",
    "lazy afternoon with a quick snack",
    "dogs and cats living together",
    "the brown dog sleeps all day",
]


@pytest.fixture
def shard_ctx(tmp_path):
    settings = Settings.from_flat({})
    svc = MapperService(settings)
    e = Engine(str(tmp_path / "shard0"), svc)
    for i, text in enumerate(DOCS):
        e.index("doc", str(i), {"body": text})
    e.refresh()
    return ShardContext(e.acquire_searcher(), svc,
                        SimilarityService(settings, mapper_service=svc))


def test_second_identical_query_zero_recompiles(shard_ctx):
    q = parse_query({"match": {"body": "quick brown fox"}})
    warm = search_shard(shard_ctx, q, k=5)  # first run may compile freely
    with sanitize(max_compiles=0, transfers="disallow") as rep:
        again = search_shard(shard_ctx, q, k=5)
    assert rep.compiles == 0, rep.compile_events
    assert again.hits == warm.hits
    assert again.total == warm.total


def test_compile_counter_sees_fresh_compile():
    with sanitize(transfers="off") as rep:
        # a brand-new wrapper object can't hit any jit cache
        jax.jit(lambda x: x * 3.25 + 1.0)(jnp.ones(7)).block_until_ready()
    assert rep.compiles >= 1
    assert all("compile" in e for e in rep.compile_events)


def test_compile_budget_trips():
    with pytest.raises(CompileBudgetExceeded):
        with sanitize(max_compiles=0, transfers="off"):
            jax.jit(lambda x: x * 2.5 - 3.0)(jnp.ones(5)).block_until_ready()


def test_transfer_guard_blocks_implicit_pull():
    x = jnp.arange(8, dtype=jnp.float32)
    with pytest.raises(Exception, match="[Dd]isallow"):
        with sanitize(transfers="disallow"):
            float(x[0])  # tpulint: ignore[TPU001] — the TP this test exists for


def test_transfer_guard_allows_batched_explicit_pull():
    x = jnp.arange(8, dtype=jnp.float32)
    with sanitize(transfers="disallow") as rep:
        host = jax.device_get(x)  # the sanctioned batched idiom
        vals = host.tolist()
    assert vals == list(range(8))
    assert isinstance(rep, SanitizerReport)


def test_nested_scopes_count_independently():
    with sanitize(transfers="off") as outer:
        jax.jit(lambda x: x + 0.125)(jnp.ones(3)).block_until_ready()
        with sanitize(transfers="off") as inner:
            pass  # nothing compiles in here
    assert outer.compiles >= 1
    assert inner.compiles == 0


def test_sanitizer_off_mode_is_inert():
    x = jnp.ones(4)
    with sanitize(transfers="off"):
        assert np.isfinite(float(x.sum()))  # implicit pull allowed when off


def test_env_escape_hatch_log_mode(monkeypatch):
    """ESTPU_SANITIZE=log downgrades the default hard guard to warn-only —
    the debugging escape hatch documented in jaxenv.sanitize()."""
    monkeypatch.setenv("ESTPU_SANITIZE", "log")
    x = jnp.arange(4, dtype=jnp.float32)
    with sanitize() as rep:
        val = float(x[0])  # tpulint: ignore[TPU001] — must only WARN under log
    assert val == 0.0
    assert isinstance(rep, SanitizerReport)


def test_env_compile_budget_is_hard(monkeypatch):
    """ESTPU_COMPILE_BUDGET is enforced (not just counted) when sanitize()
    is entered without an explicit max_compiles — the conftest gate's knob."""
    monkeypatch.setenv("ESTPU_COMPILE_BUDGET", "0")
    with pytest.raises(CompileBudgetExceeded):
        with sanitize(transfers="off"):
            jax.jit(lambda x: x * 7.5 + 0.25)(jnp.ones(6)).block_until_ready()


# ---------------------------------------------------------------------------
# the SPMD collective path on a 1-device mesh: runtime + static, together
# ---------------------------------------------------------------------------


def test_mesh_collective_path_warm_and_tpu006_clean(shard_ctx):
    """The serving loop over the shard_map'd program (psum DFS + all_gather
    reduce, parallel/mesh_search.py) on a 1-DEVICE mesh: after warming, a
    repeat of the same search must run with 0 recompiles and no implicit
    transfers under the hard guard. Statically, the deduped tpulint corpus
    check over the collective paths (mesh_serving + mesh_search) must carry
    0 TPU006 findings — the dynamic and static halves of the same invariant."""
    import os as _os

    from jax.sharding import Mesh

    from elasticsearch_tpu.parallel.mesh_search import (
        MeshSearchExecutor,
        build_sharded_index,
    )
    from elasticsearch_tpu.search import parse_query
    from elasticsearch_tpu.search.execute import lower_flat

    mesh = Mesh(np.array(jax.devices()[:1]), ("shards",))
    sidx = build_sharded_index([shard_ctx.searcher], fields=["body"], mesh=mesh)
    ex = MeshSearchExecutor(sidx, mesh, similarity="BM25")
    plan = lower_flat(parse_query({"match": {"body": "quick brown fox"}}),
                      shard_ctx)
    assert plan is not None
    warm = ex.search([plan], k=5)  # first run compiles freely
    with sanitize(max_compiles=0, transfers="disallow") as rep:
        again = ex.search([plan], k=5)  # the warmed serving loop
    assert rep.compiles == 0, rep.compile_events
    np.testing.assert_array_equal(again.doc, warm.doc)
    np.testing.assert_array_equal(again.totals, warm.totals)

    from tools.tpulint import lint_paths

    repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    paths = [_os.path.join(repo, "elasticsearch_tpu", "parallel", f)
             for f in ("mesh_serving.py", "mesh_search.py")]
    tpu006 = [f for f in lint_paths(paths) if f.rule == "TPU006"]
    assert tpu006 == [], [f.to_dict() for f in tpu006]
