"""Device field sort: differential tests vs the host mask path.

Single numeric field sorts ride the fused kernel (top-k over pre-folded key
rows — ops/scoring._dense_sort_impl); only exactly-f32-representable columns
are eligible, so ordering is bit-identical to the host lexsort. Everything
else (multi-key, _score/geo/script sorts, avg/sum modes, fractional columns)
falls back to the host path.
"""

from __future__ import annotations

import math
import tempfile

import numpy as np
import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.mapper.core import MapperService
from elasticsearch_tpu.search import ShardContext
from elasticsearch_tpu.search.service import (
    _try_device_sort,
    execute_query_phase,
    parse_search_body,
)
from elasticsearch_tpu.search.similarity import SimilarityService


@pytest.fixture(scope="module")
def ctx():
    tmp = tempfile.mkdtemp()
    svc = MapperService(Settings.from_flat({}))
    eng = Engine(tmp, svc)
    rng = np.random.default_rng(31)
    words = ["alpha", "beta", "gamma", "delta"]
    for i in range(300):
        d = {"body": " ".join(rng.choice(words, size=5)),
             "rank": int(rng.integers(0, 5000)),
             "price_frac": float(np.round(rng.uniform(1, 99), 2))}
        if i % 6 == 0:
            del d["rank"]  # missing values
        if i % 5 == 0:
            d["multi"] = [int(x) for x in rng.integers(0, 100, size=3)]
        eng.index("doc", str(i), d)
        if i == 149:
            eng.refresh()
    for i in (7, 70, 200):
        eng.delete("doc", str(i))
    eng.refresh()
    out = ShardContext(eng.acquire_searcher(), svc,
                       SimilarityService(Settings.from_flat({}), mapper_service=svc))
    yield out
    eng.close()


def _both(ctx, body, expect_device=True):
    req = parse_search_body(body)
    if expect_device:
        assert _try_device_sort(ctx, req, req.from_ + req.size, None, 0) is not None
    dev = execute_query_phase(ctx, req, use_device=True)
    host = execute_query_phase(ctx, req, use_device=False)
    assert dev.total == host.total
    assert len(dev.docs) == len(host.docs)
    for (ds, dg, dv), (hs, hg, hv) in zip(dev.docs, host.docs):
        assert dg == hg, (body, dev.docs[:5], host.docs[:5])
        assert dv == hv
        if not (math.isnan(ds) and math.isnan(hs)):
            assert ds == pytest.approx(hs, rel=1e-6)
    if not (math.isnan(dev.max_score) and math.isnan(host.max_score)):
        assert dev.max_score == pytest.approx(host.max_score, rel=1e-6)
    return req


@pytest.mark.parametrize("order", ["asc", "desc"])
def test_basic_field_sort(ctx, order):
    _both(ctx, {"query": {"match": {"body": "alpha beta"}},
                "sort": [{"rank": order}], "size": 25})


@pytest.mark.parametrize("missing", ["_last", "_first", 42])
def test_missing_policies(ctx, missing):
    _both(ctx, {"query": {"match": {"body": "gamma"}},
                "sort": [{"rank": {"order": "asc", "missing": missing}}],
                "size": 30})


@pytest.mark.parametrize("mode,order", [("min", "desc"), ("max", "asc")])
def test_multivalued_modes(ctx, mode, order):
    _both(ctx, {"query": {"match": {"body": "delta"}},
                "sort": [{"multi": {"order": order, "mode": mode}}], "size": 20})


def test_filtered_query_with_sort(ctx):
    _both(ctx, {"query": {"filtered": {"query": {"match": {"body": "alpha"}},
                                       "filter": {"range": {"rank": {"lte": 2500}}}}},
                "sort": [{"rank": "desc"}], "size": 15})


def test_sort_with_aggs_combined(ctx):
    # sort + aggs both device-eligible: ordering from the sort launch, partials
    # from the agg launch (same match set)
    from elasticsearch_tpu.search.aggregations import reduce_aggs

    body = {"query": {"match": {"body": "alpha"}},
            "sort": [{"rank": "asc"}], "size": 10,
            "aggs": {"m": {"max": {"field": "rank"}},
                     "by_label": {"terms": {"field": "multi"}}}}
    req = _both(ctx, body)
    dev = execute_query_phase(ctx, req, use_device=True)
    host = execute_query_phase(ctx, req, use_device=False)
    dr = reduce_aggs(req.aggs, dev.agg_partials)
    hr = reduce_aggs(req.aggs, host.agg_partials)
    assert dr == hr


def test_sort_with_host_only_agg_falls_back(ctx):
    # an ineligible agg sends the whole request host-side, still correct
    _both(ctx, {"query": {"match": {"body": "alpha"}},
                "sort": [{"rank": "asc"}], "size": 5,
                "aggs": {"c": {"cardinality": {"field": "rank"}}}},
          expect_device=False)


def test_track_scores(ctx):
    _both(ctx, {"query": {"match": {"body": "beta"}},
                "sort": [{"rank": "asc"}], "size": 10, "track_scores": True})


@pytest.mark.parametrize("body", [
    # fractional column: not f32-exact → host (ordering must still agree)
    {"query": {"match": {"body": "alpha"}}, "sort": [{"price_frac": "asc"}],
     "size": 10},
    # multi-key → host
    {"query": {"match": {"body": "alpha"}},
     "sort": [{"rank": "asc"}, {"price_frac": "desc"}], "size": 10},
    # avg mode → host
    {"query": {"match": {"body": "alpha"}},
     "sort": [{"multi": {"order": "asc", "mode": "avg"}}], "size": 10},
    # _score sort → host
    {"query": {"match": {"body": "alpha"}}, "sort": ["_score"], "size": 10},
])
def test_host_fallbacks_agree(ctx, body):
    req = parse_search_body(body)
    if len(req.sort) == 1:
        assert _try_device_sort(ctx, req, 10, None, 0) is None
    _both(ctx, body, expect_device=False)


def test_serving_counters_track_paths(ctx):
    from elasticsearch_tpu.search.service import SERVING_COUNTERS

    cases = [
        ({"query": {"match": {"body": "alpha"}}, "size": 3}, "device_sparse"),
        ({"query": {"filtered": {"query": {"match": {"body": "alpha"}},
                                 "filter": {"range": {"rank": {"gte": 1}}}}},
          "size": 3}, "device_filtered"),
        ({"query": {"function_score": {"query": {"match": {"body": "alpha"}},
                                       "boost_factor": 2}}, "size": 3},
         "device_function_score"),
        ({"query": {"match": {"body": "alpha"}}, "size": 0,
          "aggs": {"m": {"max": {"field": "rank"}}}}, "device_aggs"),
        ({"query": {"match": {"body": "alpha"}}, "sort": [{"rank": "asc"}],
          "size": 3}, "device_sort"),
        ({"query": {"match": {"body": "alpha"}}, "sort": ["_score", {"rank": "asc"}],
          "size": 3}, "host"),
    ]
    for body, path in cases:
        before = SERVING_COUNTERS[path]
        execute_query_phase(ctx, parse_search_body(body), use_device=True)
        assert SERVING_COUNTERS[path] == before + 1, (path, body)
