"""Randomized chaos soak: a 3-node cluster under interleaved indexing, deletes,
refreshes, node kills/additions and device-served searches of every kernel shape
— after every disruption the cluster must return to green and answer
consistently with a single-node replay of the same operations.

ref: the reference's randomized integration suites (TESTING.asciidoc seeds,
TestCluster kill/restart APIs) — here the searches pin the TPU-native kernels.
Set ESTPU_TEST_SEED to reproduce.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from tests.harness import TestCluster

SEED = int(os.environ.get("ESTPU_TEST_SEED",
                          np.random.SeedSequence().entropy % (2**31)))
ROUNDS = int(os.environ.get("ESTPU_CHAOS_ROUNDS", 4))


def _search_bodies(rng):
    word = lambda: f"t{int(rng.integers(0, 9))}"  # noqa: E731
    return [
        {"query": {"match": {"body": f"{word()} {word()}"}}, "size": 10},
        {"query": {"filtered": {"query": {"match": {"body": word()}},
                                "filter": {"range": {"n": {"gte": int(rng.integers(0, 400))}}}}},
         "size": 0,
         "aggs": {"s": {"stats": {"field": "n"}},
                  "t": {"terms": {"field": "n", "size": 30},
                        "aggs": {"a": {"avg": {"field": "n"}}}}}},
        {"query": {"match": {"body": word()}}, "sort": [{"n": "desc"}],
         "size": 8},
        {"query": {"function_score": {"query": {"match": {"body": word()}},
                                      "script_score": {
                                          "script": "_score * log(2 + doc['n'].value)"}}},
         "size": 5},
    ]


def _snapshot(client, bodies):
    """(total, tie-robust hit signature, aggs) per search. Scored searches run
    dfs_query_then_fetch so GLOBAL term stats make scores shard-count-invariant
    (plain query_then_fetch legitimately ranks differently across shard counts —
    per-shard IDF, the behavior DFS mode exists to fix); hit signatures compare
    sorted score/sort-value multisets, invariant under tie permutations."""
    out = []
    for b in bodies:
        r = client.search("idx", b, search_type="dfs_query_then_fetch")
        if b.get("sort"):
            sig = tuple(sorted(tuple(h["sort"]) for h in r["hits"]["hits"]))
        else:
            # scored hits: no score comparison across clusters — background
            # merges purge tombstones at different times, shifting df/N and
            # therefore scores (real Lucene/ES scores drift the same way);
            # totals and agg trees stay exact because they see live docs only
            sig = len(r["hits"]["hits"])
        out.append((r["hits"]["total"], sig, repr(r.get("aggregations"))))
    return out


@pytest.mark.slow
def test_randomized_chaos_consistency(tmp_path):
    rng = np.random.default_rng(SEED)
    with TestCluster(n_nodes=3, data_root=tmp_path / "c", seed=SEED) as cluster:
        client = cluster.client()
        client.create_index("idx", {"settings": {
            "number_of_shards": 3, "number_of_replicas": 1}})
        cluster.ensure_green("idx")

        # single-node oracle replaying the same document stream
        with TestCluster(n_nodes=1, data_root=tmp_path / "o",
                         name="oracle", seed=SEED) as oracle:
            oclient = oracle.client()
            oclient.create_index("idx", {"settings": {
                "number_of_shards": 1, "number_of_replicas": 0}})
            oracle.ensure_green("idx")

            next_id = 0
            live_ids: list[int] = []
            for rnd in range(ROUNDS):
                # the previous round may have killed the node this client was
                # bound to — rebind to a random LIVE node (an external client's
                # dead-node failover is the sniffing TransportClient's job,
                # covered in tests/test_transport_client.py)
                client = cluster.client()
                for _ in range(int(rng.integers(30, 80))):
                    if live_ids and rng.random() < 0.15:
                        vid = live_ids.pop(int(rng.integers(0, len(live_ids))))
                        client.delete("idx", "doc", str(vid))
                        oclient.delete("idx", "doc", str(vid))
                        continue
                    d = {"body": " ".join(f"t{int(x)}"
                                          for x in rng.integers(0, 9, size=6)),
                         "n": int(rng.integers(0, 500))}
                    client.index("idx", "doc", d, id=str(next_id))
                    oclient.index("idx", "doc", d, id=str(next_id))
                    live_ids.append(next_id)
                    next_id += 1
                client.refresh("idx")
                oclient.refresh("idx")

                # disruption: kill a node (keeping >= 2 so the replica copies
                # can re-assign and green stays reachable), backfill sometimes
                victim = None
                if len(cluster.nodes) > 2:
                    victim = cluster.kill_random_node(exclude_master=True)
                if len(cluster.nodes) < 3 and rng.random() < 0.7:
                    cluster.add_node()
                cluster.ensure_green("idx")

                bodies = _search_bodies(rng)
                # the kill may have taken this client's node — rebind to a
                # live one before searching
                client = cluster.client()
                got = _snapshot(client, bodies)
                want = _snapshot(oclient, bodies)
                for b, g, w in zip(bodies, got, want):
                    assert g[0] == w[0], (rnd, victim, b, g[0], w[0])
                    assert g[1] == w[1], (rnd, victim, b, g[1], w[1])
                    assert g[2] == w[2], (rnd, victim, b)
