"""Runtime lock-trace sanitizer (common/locktrace.py).

The dynamic twin of the tpulint concurrency family: under ESTPU_LOCKTRACE=1,
repo-constructed locks record per-thread acquisition order and device pulls
timed under a held lock. Covered here:

- the recorder costs exactly ZERO when the env knob is off (threading.Lock is
  the pristine factory, no wrapper anywhere);
- the ABBA deadlock fixture (tests/tpulint_fixtures/tp_abba_deadlock.py —
  ALSO a TPU004 static tp fixture) fails under ESTPU_LOCKTRACE=1 with a cycle
  report naming both acquisition sites, WITHOUT ever deadlocking, and passes
  once the acquisition order is fixed;
- a warmed serving loop through the DeviceBatcher records real lock traffic
  but no lock held across jax.device_get longer than the configured
  threshold, and no order cycle (the subprocess driver at the bottom of this
  file).

Subprocesses are used wherever the tracer must be armed: installing it
patches threading.Lock process-wide, which must never leak into the rest of
the suite.
"""

import json
import os
import subprocess
import sys
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "tpulint_fixtures", "tp_abba_deadlock.py")


def _marked_lines(path):
    with open(path, encoding="utf-8") as f:
        return [i for i, ln in enumerate(f.read().splitlines(), 1)
                if "# TP" in ln]


def _run(args, env_extra=None, timeout=120):
    env = {**os.environ, **(env_extra or {})}
    env.pop("ESTPU_LOCKTRACE", None)
    env.update(env_extra or {})
    return subprocess.run([sys.executable, *args], capture_output=True,
                          text=True, cwd=REPO, timeout=timeout, env=env)


# ---------------------------------------------------------------------------
# env knob off: zero overhead, nothing patched
# ---------------------------------------------------------------------------


def test_overhead_zero_when_knob_off():
    """Importing locktrace must patch NOTHING by itself; with the knob unset,
    maybe_install is a no-op and threading.Lock stays the pristine factory.
    (When the suite itself runs under ESTPU_LOCKTRACE=1 — the acceptance mode
    — the tracer is armed instead, and the session gate checks the graph.)"""
    from elasticsearch_tpu.common import locktrace

    if os.environ.get("ESTPU_LOCKTRACE", "") in ("1", "on", "true"):
        assert locktrace.TRACER.enabled
        assert threading.Lock is locktrace._traced_lock_factory
        return
    assert locktrace.maybe_install() is None
    assert not locktrace.TRACER.enabled
    assert threading.Lock is locktrace._REAL_LOCK
    assert threading.RLock is locktrace._REAL_RLOCK
    # a lock constructed now is the raw primitive, no delegation layer
    assert type(threading.Lock()) is type(locktrace._REAL_LOCK())


def test_fixture_runs_clean_without_the_knob():
    res = _run([FIXTURE, "abba"])
    assert res.returncode == 0, res.stderr


# ---------------------------------------------------------------------------
# the ABBA deadlock fixture under the tracer
# ---------------------------------------------------------------------------


def test_abba_fails_with_cycle_report_naming_both_sites():
    """Two threads take (a then b) and (b then a) SEQUENTIALLY — no deadlock
    ever happens, the order graph alone proves the hazard (lockdep's trick).
    The report must name both inner acquisition sites by file:line."""
    res = _run([FIXTURE, "abba"], {"ESTPU_LOCKTRACE": "1"})
    assert res.returncode != 0
    assert "LockOrderViolation" in res.stderr
    assert "lock-order cycle" in res.stderr
    for line_no in _marked_lines(FIXTURE):
        assert f"tp_abba_deadlock.py:{line_no}" in res.stderr, \
            (line_no, res.stderr)


def test_fixed_order_passes_under_the_tracer():
    res = _run([FIXTURE, "fixed"], {"ESTPU_LOCKTRACE": "1"})
    assert res.returncode == 0, res.stderr


# ---------------------------------------------------------------------------
# warmed serving loop under the batcher: no lock held across device_get
# ---------------------------------------------------------------------------


def test_warmed_serving_loop_holds_no_lock_across_device_get():
    """Drive concurrent searches through the DeviceBatcher with the tracer
    armed and a 250 ms held-dispatch threshold: real lock traffic must be
    recorded, the order graph must stay acyclic, and no traced lock may be
    held across a jax.device_get longer than the threshold (PR-5's contract:
    the drainer's dispatch half never pulls; the merge half pulls with no
    lock held)."""
    res = _run(["-m", "tests.test_locktrace"],
               {"ESTPU_LOCKTRACE": "1", "ESTPU_LOCKTRACE_HELD_MS": "250"},
               timeout=420)
    assert res.returncode == 0, res.stdout + res.stderr
    snap = json.loads(res.stdout.splitlines()[-1])
    assert snap["locks_created"] > 0
    assert snap["acquisitions"] > 0
    assert snap["long_held"] == [], snap["long_held"]


def _serving_driver() -> int:
    from elasticsearch_tpu.common.jaxenv import force_cpu_platform

    force_cpu_platform(n_devices=1)

    from elasticsearch_tpu.common.locktrace import TRACER, maybe_install

    maybe_install()
    assert TRACER.enabled, "driver requires ESTPU_LOCKTRACE=1"

    import tempfile

    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.index import Engine
    from elasticsearch_tpu.mapper import MapperService
    from elasticsearch_tpu.search import ShardContext, parse_query
    from elasticsearch_tpu.search.batcher import DeviceBatcher
    from elasticsearch_tpu.search.execute import lower_flat
    from elasticsearch_tpu.search.similarity import SimilarityService

    words = ["quick", "brown", "fox", "lazy", "dog", "summer", "red", "bear"]
    settings = Settings.from_flat({})
    svc = MapperService(settings)
    with tempfile.TemporaryDirectory() as td:
        engine = Engine(os.path.join(td, "shard0"), svc)
        for i in range(48):
            engine.index("doc", str(i), {
                "body": f"{words[i % 8]} {words[(i + 1) % 8]} {words[(i + 3) % 8]}"})
        engine.refresh()
        ctx = ShardContext(engine.acquire_searcher(), svc,
                           SimilarityService(settings, mapper_service=svc))
        batcher = DeviceBatcher(Settings.from_flat(
            {"search.batch.linger_ms": "2", "search.batch.max_batch": "8"}))
        try:
            texts = [f"{a} {b}" for a in words[:4] for b in words[4:]]
            plans = {t: lower_flat(parse_query({"match": {"body": t}}), ctx)
                     for t in texts}
            # warm both the lone-request and the coalesced shapes
            batcher.execute(plans[texts[0]], ctx, 10)

            def worker(t):
                td_ = batcher.execute(plans[t], ctx, 10)
                assert td_ is not None

            for _round in range(3):
                threads = [threading.Thread(target=worker, args=(t,))
                           for t in texts[:8]]
                for th in threads:
                    th.start()
                for th in threads:
                    th.join(60)
        finally:
            batcher.shutdown()
        engine.close()

    TRACER.check()  # any runtime lock-order cycle fails the driver
    snap = TRACER.snapshot()
    assert snap["acquisitions"] > 0, snap
    print(json.dumps(snap))
    return 0


if __name__ == "__main__":
    sys.exit(_serving_driver())
