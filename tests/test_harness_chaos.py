"""Chaos tests on the TestCluster harness: master kill + failover, replica
promotion under node death, store fault injection during recovery.

ref: the reference's recovery/discovery/cluster suites run on TestCluster with
stopRandomNode; MockFSDirectoryService injects random IO errors."""

import time

import pytest

from tests.harness import FaultyStore, SearcherLeakTracker, TestCluster


class TestClusterHarness:
    def test_master_kill_reelection_and_data_survival(self, tmp_path):
        with TestCluster(n_nodes=3, data_root=tmp_path, seed=7) as cluster:
            c = cluster.client()
            c.create_index("ha", {"settings": {"number_of_shards": 2,
                                               "number_of_replicas": 1}})
            cluster.ensure_green("ha")
            for i in range(20):
                c.index("ha", "doc", {"n": i}, id=str(i))
            c.refresh("ha")
            old_master = cluster.master_name()
            cluster.kill_node(old_master)
            # a new master must emerge and all data must survive via replicas
            deadline = time.time() + 30
            while time.time() < deadline:
                m = cluster.master_name()
                if m is not None and m != old_master:
                    break
                time.sleep(0.2)
            assert cluster.master_name() not in (None, old_master)
            deadline = time.time() + 30
            count = 0
            while time.time() < deadline:
                try:
                    cluster.client().refresh("ha")
                    count = cluster.client().count("ha")["count"]
                    if count == 20:
                        break
                except Exception:  # noqa: BLE001 — cluster still settling
                    pass
                time.sleep(0.3)
            assert count == 20

    def test_node_join_rebalances_and_serves(self, tmp_path):
        with TestCluster(n_nodes=2, data_root=tmp_path, seed=3) as cluster:
            c = cluster.client()
            c.create_index("grow", {"settings": {"number_of_shards": 3,
                                                 "number_of_replicas": 1}})
            cluster.ensure_green("grow")
            for i in range(12):
                c.index("grow", "doc", {"n": i}, id=str(i))
            c.refresh("grow")
            cluster.add_node()
            cluster.client().cluster_health(wait_for_nodes=3)
            assert cluster.client().count("grow")["count"] == 12


class TestFaultInjection:
    def test_store_read_faults_surface_not_corrupt(self, tmp_path):
        """Injected read IOErrors must raise cleanly (checksummed store), never
        return corrupt segments."""
        from elasticsearch_tpu.common.settings import Settings
        from elasticsearch_tpu.index.engine import Engine
        from elasticsearch_tpu.mapper.core import MapperService

        svc = MapperService(Settings.from_flat({}))
        eng = Engine(str(tmp_path / "f"), svc)
        for i in range(30):
            eng.index("doc", str(i), {"n": i})
        eng.refresh()
        eng.flush()
        eng.close()

        eng2 = Engine(str(tmp_path / "f"), svc)
        faulty = FaultyStore(eng2.store, fail_rate=1.0)
        eng2.store = faulty
        with pytest.raises(IOError):
            eng2.recover_from_store()
        assert faulty.failures > 0
        # with faults off, the same store recovers fully
        faulty.fail_rate = 0.0
        eng3 = Engine(str(tmp_path / "f"), svc)
        eng3.recover_from_store()
        eng3.refresh()
        assert eng3.acquire_searcher().max_doc == 30
        eng3.close()

    def test_searcher_acquisitions_bounded_per_search(self, tmp_path):
        from elasticsearch_tpu.common.settings import Settings
        from elasticsearch_tpu.index.engine import Engine
        from elasticsearch_tpu.mapper.core import MapperService
        from elasticsearch_tpu.search import ShardContext, parse_query, search_shard
        from elasticsearch_tpu.search.similarity import SimilarityService

        settings = Settings.from_flat({})
        svc = MapperService(settings)
        eng = Engine(str(tmp_path / "lk"), svc)
        for i in range(10):
            eng.index("doc", str(i), {"t": "leak check"})
        eng.refresh()
        with SearcherLeakTracker(eng) as tracker:
            ctx = ShardContext(eng.acquire_searcher(), svc,
                               SimilarityService(settings, mapper_service=svc))
            for _ in range(5):
                search_shard(ctx, parse_query({"match": {"t": "leak"}}), 5,
                             use_device=False)
            # a search must not re-acquire per hit/segment — one per context
            assert tracker.acquired <= 2, tracker.acquired
        eng.close()
