"""monitor.py /proc parsing (os/process/fs stats) against canned fixtures.

The monitor service feeds the os/process/fs sections of `/_nodes/stats`; its
parsing was previously untested — a /proc format drift would silently zero
operator dashboards. Fixtures here pin the exact dict shape the stats API
serves (the `proc=` override added for this test reads a fake procfs root)."""

import os
import types

from elasticsearch_tpu.monitor import (
    MonitorService,
    fs_stats,
    os_stats,
    process_stats,
    runtime_stats,
)

MEMINFO = """MemTotal:       16265540 kB
MemFree:         1543732 kB
MemAvailable:    9853212 kB
Buffers:          734372 kB
Cached:          6754120 kB
SwapTotal:       2097148 kB
SwapFree:        2097000 kB
"""

SELF_STATUS = """Name:\tpython
Umask:\t0022
State:\tR (running)
Threads:\t17
VmPeak:\t  902340 kB
VmRSS:\t  345678 kB
voluntary_ctxt_switches:\t100
"""


def _fake_proc(tmp_path):
    proc = tmp_path / "proc"
    (proc / "self" / "fd").mkdir(parents=True)
    (proc / "meminfo").write_text(MEMINFO)
    (proc / "self" / "status").write_text(SELF_STATUS)
    for i in range(5):
        (proc / "self" / "fd" / str(i)).write_text("")
    return str(proc)


class TestOsStats:
    def test_meminfo_parsed_to_bytes(self, tmp_path):
        out = os_stats(proc=_fake_proc(tmp_path))
        assert out["mem"]["total_in_bytes"] == 16265540 * 1024
        assert out["mem"]["free_in_bytes"] == 1543732 * 1024
        assert out["mem"]["available_in_bytes"] == 9853212 * 1024
        assert out["swap"]["total_in_bytes"] == 2097148 * 1024
        assert out["swap"]["free_in_bytes"] == 2097000 * 1024
        assert out["cpu"]["count"] == os.cpu_count()
        assert isinstance(out["timestamp"], int)

    def test_missing_meminfo_degrades_gracefully(self, tmp_path):
        # an empty proc root (no meminfo at all) must not raise — the stats
        # dict just omits the mem/swap sections
        out = os_stats(proc=str(tmp_path))
        assert "mem" not in out
        assert "cpu" in out


class TestProcessStats:
    def test_status_threads_rss_and_fds(self, tmp_path):
        out = process_stats(proc=_fake_proc(tmp_path))
        assert out["threads"] == 17
        assert out["mem"]["resident_in_bytes"] == 345678 * 1024
        assert out["open_file_descriptors"] == 5
        assert out["max_file_descriptors"] >= 5
        assert out["id"] == os.getpid()
        cpu = out["cpu"]
        # total is computed from the float sum; per-part values truncate, so
        # allow the 1ms-per-part rounding skew
        assert abs(cpu["total_in_millis"]
                   - (cpu["user_in_millis"] + cpu["sys_in_millis"])) <= 2

    def test_missing_status_keeps_rusage_fallback(self, tmp_path):
        out = process_stats(proc=str(tmp_path))
        # no /proc/self/status fixture: VmRSS fallback is getrusage maxrss
        assert out["mem"]["resident_in_bytes"] > 0
        assert "threads" not in out


class TestFsStats:
    def test_statvfs_shape(self, tmp_path):
        out = fs_stats([str(tmp_path)])
        assert len(out["data"]) == 1
        entry = out["data"][0]
        assert entry["path"] == str(tmp_path)
        assert entry["total_in_bytes"] >= entry["free_in_bytes"] >= 0
        assert entry["free_in_bytes"] >= entry["available_in_bytes"] >= 0

    def test_bad_path_skipped(self, tmp_path):
        out = fs_stats([str(tmp_path / "definitely-not-there")])
        assert out["data"] == []


class TestFullStats:
    def test_nodes_stats_sections_shape(self, tmp_path):
        """The exact section set /_nodes/stats spreads into the node dict."""
        svc = MonitorService(types.SimpleNamespace(data_path=str(tmp_path)))
        out = svc.full_stats()
        assert set(out) == {"os", "process", "fs", "runtime"}
        assert "cpu" in out["os"]
        assert out["process"]["id"] == os.getpid()
        rt = runtime_stats()
        assert rt["runtime"] == "python"
        assert isinstance(rt["devices"], list)
