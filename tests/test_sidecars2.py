"""Round-4 sidecars: resource watcher + file scripts, bulk-UDP, jax profiler REST.

ref: watcher/ResourceWatcherService.java:42, bulk/udp/BulkUdpService.java,
SURVEY §5.1 (device-side tracing)."""

import json
import os
import socket
import time

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.transport.local import LocalTransportRegistry
from elasticsearch_tpu.watcher import (
    FileChangesListener,
    FileWatcher,
    ResourceWatcherService,
    ScriptDirectoryListener,
)


class _Recorder(FileChangesListener):
    def __init__(self):
        self.events = []

    def on_file_created(self, path):
        self.events.append(("created", os.path.basename(path)))

    def on_file_changed(self, path):
        self.events.append(("changed", os.path.basename(path)))

    def on_file_deleted(self, path):
        self.events.append(("deleted", os.path.basename(path)))


class TestFileWatcher:
    def test_create_change_delete_cycle(self, tmp_path):
        rec = _Recorder()
        w = FileWatcher(str(tmp_path), rec)
        w.init()
        p = tmp_path / "a.txt"
        p.write_text("one")
        w.check()
        assert ("created", "a.txt") in rec.events
        time.sleep(0.01)
        p.write_text("two longer")
        w.check()
        assert ("changed", "a.txt") in rec.events
        p.unlink()
        w.check()
        assert ("deleted", "a.txt") in rec.events

    def test_service_polls_registered_watchers(self, tmp_path):
        from elasticsearch_tpu.common.settings import Settings

        svc = ResourceWatcherService(Settings.from_flat({"watcher.interval": 600}))
        rec = _Recorder()
        svc.add(FileWatcher(str(tmp_path), rec))
        (tmp_path / "x").write_text("x")
        svc.notify_now()
        assert ("created", "x") in rec.events


class TestFileScripts:
    def test_scripts_dir_hot_reload(self, tmp_path):
        node = Node(name="ws1", registry=LocalTransportRegistry(),
                    data_path=str(tmp_path))
        try:
            node.start([node.local_node.transport_address])
            node.wait_for_master()
            sdir = tmp_path / "config" / "scripts"
            sdir.mkdir(parents=True)
            (sdir / "double_it.expression").write_text("x * 2")
            node.resource_watcher.notify_now()
            cs = node.script_service.compile("double_it", {"x": 21})
            assert cs({}) == 42  # named file script resolved + sandbox-compiled
            # module-level compile sites (sort/functions/aggs) resolve names too
            from elasticsearch_tpu.script import compile_script

            assert compile_script("double_it", {"x": 4})({}) == 8
            # hot change
            (sdir / "double_it.expression").write_text("x * 3")
            node.resource_watcher.notify_now()
            assert node.script_service.compile("double_it", {"x": 10})({}) == 30
        finally:
            node.close()


class TestScriptRegistryIsolation:
    def test_one_services_delete_spares_anothers_script(self):
        from elasticsearch_tpu.script import ScriptService, compile_script

        s1, s2 = ScriptService(), ScriptService()
        s1.put("shared_calc", "x + 1")
        s2.put("shared_calc", "x + 1")
        s1.remove("shared_calc")  # node A's file deleted
        # node B's registration survives; module-level resolution still works
        assert compile_script("shared_calc", {"x": 1})({}) == 2
        s2.remove("shared_calc")
        # now unresolvable → treated as inline source (and "shared_calc" isn't
        # a valid expression → compile error)
        import pytest as _pytest

        from elasticsearch_tpu.script import ScriptError

        with _pytest.raises(ScriptError):
            compile_script("shared_calc!", {})


class TestBulkUdp:
    def test_datagrams_become_documents(self, tmp_path):
        node = Node(name="bu1", registry=LocalTransportRegistry(),
                    settings={"bulk.udp.enabled": True,
                              "bulk.udp.port": "19700-19720",
                              "bulk.udp.flush_interval": 0.2},
                    data_path=str(tmp_path))
        try:
            node.start([node.local_node.transport_address])
            node.wait_for_master()
            c = node.client()
            c.create_index("udp", {"settings": {"number_of_shards": 1,
                                                "number_of_replicas": 0}})
            c.cluster_health(wait_for_status="green")
            assert node.bulk_udp.port is not None
            payload = "\n".join([
                json.dumps({"index": {"_index": "udp", "_type": "doc", "_id": "1"}}),
                json.dumps({"n": 1}),
                json.dumps({"index": {"_index": "udp", "_type": "doc", "_id": "2"}}),
                json.dumps({"n": 2}),
            ]) + "\n"
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.sendto(payload.encode(), ("127.0.0.1", node.bulk_udp.port))
            s.close()
            deadline = time.time() + 10
            total = 0
            while time.time() < deadline:
                c.refresh("udp")
                total = c.count("udp")["count"]
                if total == 2:
                    break
                time.sleep(0.2)
            assert total == 2
        finally:
            node.close()

    def test_disabled_by_default(self, tmp_path):
        node = Node(name="bu2", registry=LocalTransportRegistry(),
                    data_path=str(tmp_path))
        try:
            node.start([node.local_node.transport_address])
            assert node.bulk_udp.port is None
        finally:
            node.close()


class TestProfilerRest:
    def test_start_stop_capture(self, tmp_path):
        import urllib.request

        node = Node(name="pf1", registry=LocalTransportRegistry(),
                    data_path=str(tmp_path))
        try:
            node.start([node.local_node.transport_address])
            node.wait_for_master()
            http = node.start_http(0)
            base = f"http://127.0.0.1:{http.port}"

            def post(path, body=None):
                req = urllib.request.Request(
                    base + path, data=json.dumps(body or {}).encode(),
                    method="POST", headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=30) as r:
                        return r.status, json.loads(r.read().decode())
                except urllib.error.HTTPError as e:
                    return e.code, json.loads(e.read().decode())

            s, r = post("/_nodes/_local/profiler/start")
            assert s == 200 and r["started"]
            # run some device work so the trace has content
            c = node.client()
            c.create_index("pf", {"settings": {"number_of_shards": 1,
                                               "number_of_replicas": 0}})
            c.cluster_health(wait_for_status="green")
            c.index("pf", "doc", {"t": "trace me"}, id="1")
            c.refresh("pf")
            c.search("pf", {"query": {"match": {"t": "trace"}}})
            s2, r2 = post("/_nodes/_local/profiler/stop")
            assert s2 == 200 and r2["stopped"]
            assert any(f.endswith(".pb") or "trace" in f.lower()
                       for f in r2["files"]), r2["files"]
            # double stop → 400
            s3, _r3 = post("/_nodes/_local/profiler/stop")
            assert s3 == 400
        finally:
            node.close()
