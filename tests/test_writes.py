"""Heavy-write serving (ISSUE 14): incremental device-index packing — delta
packs, off-query-path packing, and background device compaction.

Unit half: pack-ledger kind/pool vocabulary (delta_pack/compact + pool
attribution), the in-flight pack Future coordination (a racing search WAITS
instead of duplicating the pack; a cancelled warm unblocks; copy-on-write
views drop stale futures), compaction concat parity (bitwise-identical
planes vs pack_segment(merged), tf-rung widening, the exact breaker
estimate, every ineligibility fallback), the off-lock merge (acquire_searcher
never blocks on merge compute; a concurrent tombstone ABORTS the publish
instead of resurrecting the delete), the incremental _uid_index update, and
request-cache hot-key tracking.

Chaos half (live cluster): a warmed continuous-indexing loop serves with
ZERO query-path packs (ledger pool attribution + 0 recompiles under hard
transfer_guard("disallow")), base+delta scores are bitwise-identical to a
cold monolithic repack, a fielddata breaker trip during a delta pack
degrades to the host scorer (correct results, no 5xx), a compaction
publishing mid-search serves the old view while searches complete un-blocked,
recovery replays onto delta-aware packs, the warmer re-primes the request
cache so the first post-refresh sighting of a hot body is a HIT, and the
`/_nodes/stats` warmer section + `/{index}/_stats` device stanza report the
new rows.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.segment import SegmentBuilder, merge_segments
from elasticsearch_tpu.mapper import MapperService
from elasticsearch_tpu.ops.device_index import (
    BLOCK,
    PACK_LEDGER,
    PackLedger,
    begin_warm,
    cancel_warm,
    concat_estimate_bytes,
    concat_source_packs,
    pack_segment,
    pack_segment_concat,
    pack_shape_math,
    packed_for,
    run_warm,
    tf_plane_itemsize,
)
from elasticsearch_tpu.search.request_cache import (ShardRequestCache,
                                                    request_fingerprint)

from .harness import TestCluster

pytestmark = pytest.mark.writes


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _mapper():
    return MapperService(Settings.EMPTY)


def _segment(svc, gen: int, n: int, off: int = 0, seed: int = 0,
             frac_tf: bool = False, big_tf: bool = False):
    mapper = svc.mapper_for("doc")
    rng = np.random.default_rng(seed + gen)
    b = SegmentBuilder(gen)
    for i in range(n):
        words = " ".join(
            f"w{int(rng.integers(0, 25))}"
            for _ in range(int(rng.integers(2, 10))))
        if big_tf:
            words += " w0" * 300  # tf > 255 → i16 rung
        doc = mapper.parse({"body": words, "tag": f"t{(i + off) % 3}",
                            "n": i + off}, str(i + off))
        b.add(doc, version=1)
    seg = b.freeze()
    if frac_tf:
        seg.post_freqs = seg.post_freqs + np.float32(0.5)  # non-integral f32
    return seg


def _pack_live(seg):
    seg._device_cache["packed"] = pack_segment(seg)
    seg._device_cache["live"] = True
    return seg._device_cache["packed"]


def _pull(*planes):
    import jax

    return jax.device_get(list(planes))


# ---------------------------------------------------------------------------
# pack ledger: kind + pool vocabulary
# ---------------------------------------------------------------------------


class TestPackLedgerKinds:
    def test_kind_counters_and_pool_rows(self):
        led = PackLedger()
        led.record("i", 1, 1.0, 10, "u8", kind="pack", pool="search")
        led.record("i", 2, 1.0, 10, "u8", kind="delta_pack", pool="warmer")
        led.record("i", 3, 1.0, 10, "u8", kind="remask", pool="warmer")
        led.record("i", 4, 1.0, 30, "u8", kind="compact", pool="merge",
                   method="concat")
        st = led.stats("i")
        assert st["packs"] == 1 and st["delta_packs"] == 1
        assert st["remasks"] == 1 and st["compacts"] == 1
        assert st["pools"] == {"search": 1, "warmer": 2, "merge": 1}
        kinds = [e["kind"] for e in st["recent"]]
        assert kinds == ["pack", "delta_pack", "remask", "compact"]
        assert st["recent"][-1]["method"] == "concat"

    def test_pool_defaults_to_thread_name(self):
        led = PackLedger()
        led.record("i", 1, 1.0, 10, "u8")  # test main thread
        assert led.stats("i")["pools"] == {"other": 1}
        out = {}

        def work():
            led.record("i", 2, 1.0, 10, "u8", kind="delta_pack")
            out["pools"] = led.stats("i")["pools"]

        t = threading.Thread(target=work, name="estpu[warmer]_0")
        t.start()
        t.join(5)
        assert out["pools"] == {"other": 1, "warmer": 1}


# ---------------------------------------------------------------------------
# in-flight pack coordination
# ---------------------------------------------------------------------------


class TestPackCoordination:
    def test_racing_search_waits_for_actively_running_pack(self, monkeypatch):
        """A search hitting a segment whose pack is actively RUNNING on
        another thread parks on the in-flight future and gets THE same
        object — exactly one pack runs."""
        from elasticsearch_tpu.ops import device_index as di

        svc = _mapper()
        seg = _segment(svc, 1, 20)
        gate = threading.Event()
        started = threading.Event()
        real_pack = di.pack_segment

        def gated_pack(s, *a, **k):
            started.set()
            gate.wait(5)
            return real_pack(s, *a, **k)

        monkeypatch.setattr(di, "pack_segment", gated_pack)
        results = []
        owner = threading.Thread(
            target=lambda: results.append(packed_for(seg)))
        owner.start()
        assert started.wait(5)  # owner claimed and is packing
        waiter = threading.Thread(
            target=lambda: results.append(packed_for(seg)))
        waiter.start()
        time.sleep(0.05)
        assert waiter.is_alive()  # parked on the future, not duplicating
        gate.set()
        owner.join(10)
        waiter.join(10)
        assert len(results) == 2 and results[0] is results[1]

    def test_search_steals_unstarted_warm_pack(self):
        """The deadlock-proofing half of the claimable-future protocol: a
        pack SCHEDULED but not yet started is claimed by the first arriving
        search, which packs inline and resolves the shared future; the warm
        task then returns without waiting (no pool slot is ever parked on
        work queued behind it)."""
        svc = _mapper()
        seg = _segment(svc, 1, 20)
        fut = begin_warm(seg)
        assert fut is not None
        assert begin_warm(seg) is None  # deduped while in flight
        packed = packed_for(seg)  # steals the claim, packs inline
        assert fut.done() and fut.result() is packed
        assert run_warm(seg, fut) is None  # late worker: nothing to do

    def test_cancel_warm_unblocks_query_path(self):
        svc = _mapper()
        seg = _segment(svc, 1, 10)
        fut = begin_warm(seg)
        cancel_warm(seg, fut)  # pool rejected the task
        packed = packed_for(seg)  # packs inline, no deadlock
        assert packed is seg._device_cache["packed"]

    def test_with_deletes_view_drops_stale_future(self):
        svc = _mapper()
        seg = _segment(svc, 1, 10)
        fut = begin_warm(seg)
        view = seg.with_deletes([0])
        assert view._device_cache.get("pack_future") is None
        run_warm(seg, fut)  # old view's pack completes normally
        assert seg._device_cache.get("pack_future") is None

    def test_warm_failure_propagates_then_retries_inline(self, monkeypatch):
        from elasticsearch_tpu.ops import device_index as di

        svc = _mapper()
        seg = _segment(svc, 1, 10)
        fut = begin_warm(seg)
        monkeypatch.setattr(di, "pack_segment",
                            lambda *a, **k: (_ for _ in ()).throw(
                                RuntimeError("boom")))
        with pytest.raises(RuntimeError):
            run_warm(seg, fut)
        monkeypatch.undo()
        packed = packed_for(seg)  # marker was cleared: inline retry works
        assert packed.doc_count == seg.doc_count


# ---------------------------------------------------------------------------
# compaction concat pack
# ---------------------------------------------------------------------------


class TestConcatPack:
    def _parity(self, sources, gen=99):
        for s in sources:
            _pack_live(s)
        merged = merge_segments(sources, gen)
        ref = pack_segment(merged)
        got = pack_segment_concat(merged, sources)
        assert got is not None, "concat unexpectedly ineligible"
        a = _pull(ref.blk_docs, ref.blk_tf, ref.blk_nb,
                  got.blk_docs, got.blk_tf, got.blk_nb)
        assert np.array_equal(a[0], a[3])
        assert np.array_equal(a[1], a[4]) and a[1].dtype == a[4].dtype
        assert np.array_equal(a[2], a[5])
        assert np.array_equal(ref.term_blk_start, got.term_blk_start)
        assert np.array_equal(ref.host_docs, got.host_docs)
        assert np.array_equal(ref.host_freqs, got.host_freqs)
        assert np.array_equal(ref.blk_field, got.blk_field)
        lp = _pull(ref.live_parent, got.live_parent)
        assert np.array_equal(lp[0], lp[1])
        assert ref.tf_layout == got.tf_layout
        return got

    def test_bitwise_parity_three_sources(self):
        svc = _mapper()
        self._parity([_segment(svc, 1, 37), _segment(svc, 2, 21, off=100),
                      _segment(svc, 3, 5, off=200)])

    def test_tf_rung_widening_u8_to_i16(self):
        svc = _mapper()
        got = self._parity([_segment(svc, 1, 10), _segment(svc, 2, 6,
                                                           off=50,
                                                           big_tf=True)])
        assert got.tf_layout == "i16"

    def test_estimate_exact_for_concat_layout(self):
        svc = _mapper()
        sources = [_segment(svc, 1, 30), _segment(svc, 2, 12, off=100)]
        for s in sources:
            _pack_live(s)
        merged = merge_segments(sources, 9)
        NBpad, Dpad, layout = pack_shape_math(merged)
        tf_b = tf_plane_itemsize(layout)
        W, T = len(sources), len(merged.post_offsets) - 1
        expect = (NBpad * BLOCK * ((4 + 4) + (4 + tf_b + 1) + 8)
                  + NBpad * 4 * 2 + (2 * W + 1) * T * 4 * 2 + Dpad * 2
                  + Dpad * len(merged.norms) + Dpad * 8 * len(merged.dv_num))
        assert concat_estimate_bytes(merged, sources) == expect

    def test_ineligible_tombstoned_source(self):
        svc = _mapper()
        a, b = _segment(svc, 1, 20), _segment(svc, 2, 10, off=50)
        _pack_live(a)
        _pack_live(b)
        a2 = a.with_deletes([3])
        a2._device_cache["live"] = True  # even remasked: still ineligible
        assert concat_source_packs([a2, b]) is None
        merged = merge_segments([a2, b], 9)
        assert pack_segment_concat(merged, [a2, b]) is None

    def test_ineligible_fractional_f32(self):
        svc = _mapper()
        a = _segment(svc, 1, 12, frac_tf=True)
        b = _segment(svc, 2, 8, off=50)
        _pack_live(a)
        _pack_live(b)
        assert a._device_cache["packed"].tf_layout == "f32"
        assert not a._device_cache["packed"].tf_integral
        assert concat_source_packs([a, b]) is None

    def test_ineligible_unpacked_source(self):
        svc = _mapper()
        a, b = _segment(svc, 1, 10), _segment(svc, 2, 10, off=50)
        _pack_live(a)  # b never packed
        assert concat_source_packs([a, b]) is None

    def test_warm_compact_uses_concat_and_ledger_records_it(self, tmp_path):
        """Engine merge publish plants the compact hint; the warm pack takes
        the concat path and the ledger shows kind=compact method=concat."""
        from tests.test_merge_policy import build_engine

        e, svc = build_engine(tmp_path, {
            "index.merge.policy.segments_per_tier": 2})
        for i in range(12):
            e.index("doc", str(i), {"body": f"alpha w{i % 4} common"})
            if i % 3 == 2:
                e.refresh()
        for seg in e.acquire_searcher().segments:
            _pack_live(seg)
        e.maybe_merge(max_merges=1)
        searcher = e.acquire_searcher()
        merged = next(s for s in searcher.segments
                      if s._device_cache.get("pack_hint", {}).get("kind")
                      == "compact")
        fut = begin_warm(merged)
        PACK_LEDGER.forget("cc-test")
        run_warm(merged, fut, owner="cc-test")
        st = PACK_LEDGER.stats("cc-test")
        assert st["compacts"] == 1
        assert st["recent"][-1]["method"] == "concat"
        assert merged._device_cache.get("pack_hint") is None  # refs dropped
        PACK_LEDGER.forget("cc-test")


# ---------------------------------------------------------------------------
# off-lock merge + incremental uid index
# ---------------------------------------------------------------------------


class TestMergeOffLock:
    def test_search_not_blocked_by_merge_compute(self, tmp_path,
                                                 monkeypatch):
        """The acceptance pin: a search issued during a large merge completes
        without waiting for it — acquire_searcher's timed lock acquisition
        succeeds while merge_segments is still running."""
        from elasticsearch_tpu.index import engine as engine_mod

        from tests.test_merge_policy import build_engine

        e, svc = build_engine(tmp_path, {
            "index.merge.policy.segments_per_tier": 2})
        for i in range(10):
            e.index("doc", str(i), {"n": i, "body": f"alpha w{i % 3}"})
            e.refresh()
        real_merge = engine_mod.merge_segments
        in_merge = threading.Event()

        def slow_merge(segments, gen):
            in_merge.set()
            time.sleep(0.8)
            return real_merge(segments, gen)

        monkeypatch.setattr(engine_mod, "merge_segments", slow_merge)
        t = threading.Thread(target=lambda: e.maybe_merge(max_merges=1))
        t.start()
        assert in_merge.wait(5)
        t0 = time.monotonic()
        got = e._lock.acquire(timeout=0.3)
        waited = time.monotonic() - t0
        assert got, "engine lock held across merge compute"
        e._lock.release()
        assert waited < 0.3
        searcher = e.acquire_searcher()  # serves the OLD view mid-merge
        assert searcher.live_doc_count() == 10
        t.join(10)
        assert e.acquire_searcher().live_doc_count() == 10

    def test_concurrent_tombstone_aborts_publish(self, tmp_path,
                                                 monkeypatch):
        """A delete landing in a source segment mid-merge must NOT be
        resurrected by the merge publish: identity validation aborts it."""
        from elasticsearch_tpu.index import engine as engine_mod

        from tests.test_merge_policy import build_engine

        e, svc = build_engine(tmp_path, {
            "index.merge.policy.segments_per_tier": 2})
        for i in range(8):
            e.index("doc", str(i), {"n": i, "body": "alpha"})
            e.refresh()
        real_merge = engine_mod.merge_segments
        in_merge = threading.Event()
        release = threading.Event()

        def gated_merge(segments, gen):
            in_merge.set()
            release.wait(5)
            return real_merge(segments, gen)

        monkeypatch.setattr(engine_mod, "merge_segments", gated_merge)
        merges0 = e.stats["merge_total"]
        t = threading.Thread(target=lambda: e.maybe_merge(max_merges=1))
        t.start()
        assert in_merge.wait(5)
        e.delete("doc", "0")  # tombstones a doc inside the merge window
        e.refresh()
        release.set()
        t.join(10)
        # the publish aborted (no merge landed) — and the delete held
        assert e.stats["merge_total"] == merges0
        assert not e.get("doc", "0").found
        assert e.acquire_searcher().live_doc_count() == 7
        monkeypatch.undo()
        e.maybe_merge(max_merges=10)  # re-plan merges fine afterwards
        assert not e.get("doc", "0").found
        assert e.acquire_searcher().live_doc_count() == 7

    def test_uid_index_incremental_matches_full_rebuild(self, tmp_path):
        from tests.test_merge_policy import build_engine

        e, svc = build_engine(tmp_path, {
            "index.merge.policy.segments_per_tier": 2})
        for i in range(20):
            e.index("doc", str(i), {"n": i})
            e.refresh()
        e.index("doc", "5", {"n": 500})  # update: old copy dies in-window
        e.delete("doc", "7")
        e.refresh()
        e.maybe_merge(max_merges=20)
        rebuilt = {}
        for seg in e._segments:
            for local in range(seg.doc_count):
                if seg.parent_mask[local] and seg.live[local]:
                    rebuilt[f"{seg.types[local]}#{seg.ids[local]}"] = (
                        seg.gen, local)
        assert e._uid_index == rebuilt
        assert e.get("doc", "5").source["n"] == 500
        assert not e.get("doc", "7").found


# ---------------------------------------------------------------------------
# request-cache hot keys (warmer input)
# ---------------------------------------------------------------------------


class TestHotKeys:
    def _rc(self):
        return ShardRequestCache(Settings.EMPTY)

    def test_hits_rank_hot_bodies(self):
        rc = self._rc()
        bodies = [{"query": {"match": {"f": f"t{i}"}}, "size": 0}
                  for i in range(3)]
        keys = [("i", 0, 1, request_fingerprint(b)) for b in bodies]
        for k, b in zip(keys, bodies):
            rc.put(k, b"x", body=b)
        assert rc.hot_bodies("i", 0) == []  # stored but never hit
        assert not rc.has_hot("i", 0)
        for _ in range(3):
            rc.get(keys[1])
        rc.get(keys[2])
        assert rc.has_hot("i", 0)
        hot = rc.hot_bodies("i", 0, n=2)
        assert hot == [bodies[1], bodies[2]]
        # replayed bodies fingerprint identically to the live ones
        assert request_fingerprint(hot[0]) == keys[1][3]

    def test_hot_survives_view_invalidation_not_shard_drop(self):
        rc = self._rc()
        body = {"query": {"match_all": {}}, "size": 0}
        k = ("i", 0, 1, request_fingerprint(body))
        rc.put(k, b"x", body=body)
        rc.get(k)
        rc.invalidate_shard("i", 0, 2)  # view advanced
        assert rc.has_hot("i", 0)
        rc.invalidate_shard("i", 0, None)  # shard leaving the node
        assert not rc.has_hot("i", 0)

    def test_hot_bounded_per_shard(self):
        rc = self._rc()
        for i in range(rc.HOT_PER_SHARD + 10):
            b = {"query": {"match": {"f": f"t{i}"}}, "size": 0}
            rc.put(("i", 0, 1, request_fingerprint(b)), b"x", body=b)
        assert len(rc._hot[("i", 0)]) == rc.HOT_PER_SHARD


# ---------------------------------------------------------------------------
# live cluster: the write-to-serve spine
# ---------------------------------------------------------------------------


WRITES_INDEX = "wr"


def _boot(tmp_path, settings=None, index_settings=None, docs=40):
    cluster = TestCluster(n_nodes=1, data_root=tmp_path, seed=14,
                          settings=settings or {})
    cluster.start()
    c = cluster.client()
    c.create_index(WRITES_INDEX, {"settings": {
        "number_of_shards": 1, "number_of_replicas": 0,
        # deterministic view control: tests drive refresh explicitly
        "index.refresh_interval": -1, **(index_settings or {})}})
    cluster.ensure_green(WRITES_INDEX)
    for i in range(docs):
        c.index(WRITES_INDEX, "doc",
                {"body": f"alpha beta{i % 4} w{i % 7}", "n": i,
                 "tag": f"t{i % 3}"}, id=str(i))
    c.refresh(WRITES_INDEX)
    return cluster, c


def _engine(cluster):
    node = next(iter(cluster.nodes.values()))
    return node, node.indices.indices[WRITES_INDEX].shards[0].engine


def _wait(predicate, timeout=8.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


class TestLiveWrites:
    def test_warmed_loop_zero_query_path_packs_zero_recompiles(
            self, tmp_path):
        """THE acceptance pin: a warmed continuous-indexing serving loop
        under hard transfer_guard("disallow") — 0 recompiles, and every
        pack/remask lands on the warmer/merge pools (none on the query
        path), while searches stay correct as the corpus grows."""
        import jax

        from elasticsearch_tpu.common.jaxenv import sanitize

        cluster, c = _boot(tmp_path)
        try:
            node, engine = _engine(cluster)
            q = {"query": {"match": {"body": "alpha"}}, "size": 5}
            r = c.search(WRITES_INDEX, q)  # opens the pack-warming gate
            assert r["hits"]["total"] == 40
            # warm every delta shape: a couple of rounds OUTSIDE the armed
            # window compile the (stable, pow-2-bucketed) delta executables
            for rnd in range(2):
                for i in range(6):
                    c.index(WRITES_INDEX, "doc",
                            {"body": f"alpha beta{i % 4} w{i % 7}", "n": i},
                            id=f"warm{rnd}-{i}")
                c.refresh(WRITES_INDEX)
                c.search(WRITES_INDEX, q)
            assert _wait(lambda: node.warmer.stats()["packs_done"]
                         >= node.warmer.stats()["packs_scheduled"])
            PACK_LEDGER.forget(WRITES_INDEX)  # armed window sees only new
            total0 = c.search(WRITES_INDEX, q)["hits"]["total"]
            jax.config.update("jax_transfer_guard", "disallow")
            try:
                with sanitize(max_compiles=0, transfers="disallow") as rep:
                    for rnd in range(3):
                        for i in range(6):
                            c.index(WRITES_INDEX, "doc",
                                    {"body": f"alpha beta{i % 4} w{i % 7}",
                                     "n": i}, id=f"live{rnd}-{i}")
                        c.refresh(WRITES_INDEX)
                        r = c.search(WRITES_INDEX, q)
                        assert r["hits"]["total"] == total0 + 6 * (rnd + 1)
            finally:
                jax.config.update("jax_transfer_guard", "allow")
            assert rep.compiles == 0, rep.compile_events
            st = PACK_LEDGER.stats(WRITES_INDEX)
            assert st.get("delta_packs", 0) >= 3, st
            # pool attribution: ALL pack work off the query path
            assert set(st["pools"]) <= {"warmer", "merge"}, st["pools"]
            for e in st["recent"]:
                assert e["pool"] in ("warmer", "merge"), e
            # delta packs are delta-sized: far below the base segment's pack
            base_bytes = max(e["bytes"] for e in st["recent"])
            delta_bytes = [e["bytes"] for e in st["recent"]
                           if e["kind"] == "delta_pack"]
            assert delta_bytes and min(delta_bytes) <= base_bytes
        finally:
            cluster.close()

    def test_base_delta_bitwise_identical_to_cold_monolithic_repack(
            self, tmp_path):
        """Scores over base+delta segment views are BITWISE identical to a
        cold monolithic repack of the optimized index (same shard-level
        stats, same f32 op order per doc)."""
        cluster, c = _boot(tmp_path)
        try:
            node, engine = _engine(cluster)
            q = {"query": {"match": {"body": "alpha beta1"}}, "size": 20}
            for rnd in range(2):  # grow base + deltas
                for i in range(7):
                    c.index(WRITES_INDEX, "doc",
                            {"body": f"alpha beta{i % 4} w{i % 7}",
                             "n": 100 + i}, id=f"d{rnd}-{i}")
                c.refresh(WRITES_INDEX)
            assert engine.segment_count() >= 3
            before = [(h["_id"], h["_score"])
                      for h in c.search(WRITES_INDEX, q)["hits"]["hits"]]
            assert before
            c.optimize(WRITES_INDEX)
            searcher = engine.acquire_searcher()
            assert len(searcher.segments) == 1
            # force a COLD host-staged repack (drop hint + resident pack)
            seg = searcher.segments[0]
            seg._device_cache.pop("pack_hint", None)
            seg._device_cache.pop("pack_future", None)
            seg._device_cache.pop("packed", None)
            seg._device_cache.pop("live", None)
            after = [(h["_id"], h["_score"])
                     for h in c.search(WRITES_INDEX, q)["hits"]["hits"]]
            assert before == after  # ids, order, AND bitwise f32 scores
        finally:
            cluster.close()

    def test_breaker_trip_during_delta_pack_degrades_to_host(self, tmp_path):
        """Out of fielddata budget mid-delta-pack: the warm pack fails, the
        search's wait sees the trip, and the HOST scorer answers correctly —
        no 5xx, no wrong counts."""
        from elasticsearch_tpu.search.service import SERVING_COUNTERS

        cluster, c = _boot(tmp_path)
        try:
            node, engine = _engine(cluster)
            q = {"query": {"match": {"body": "alpha"}}, "size": 5}
            assert c.search(WRITES_INDEX, q)["hits"]["total"] == 40
            fd = node.breakers.breaker("fielddata")
            old_limit = fd.limit
            fd.limit = 1  # every pack estimate trips from here on
            try:
                for i in range(5):
                    c.index(WRITES_INDEX, "doc",
                            {"body": "alpha fresh", "n": i}, id=f"t{i}")
                c.refresh(WRITES_INDEX)
                host0 = SERVING_COUNTERS.get("host", 0)
                r = c.search(WRITES_INDEX, q)
                assert r["hits"]["total"] == 45
                assert SERVING_COUNTERS.get("host", 0) > host0
                assert node.warmer.stats()["pack_failures"] >= 1
            finally:
                fd.limit = old_limit
            # budget restored: device packing resumes on the next sighting
            r = c.search(WRITES_INDEX, q)
            assert r["hits"]["total"] == 45
        finally:
            cluster.close()

    def test_compaction_publish_mid_search_serves_old_view(self, tmp_path,
                                                           monkeypatch):
        """A search issued during a large merge completes without waiting
        for it (timed), the pre-publish searcher keeps serving, and the
        compaction pack lands on the merge pool via device concat."""
        from elasticsearch_tpu.index import engine as engine_mod

        cluster, c = _boot(tmp_path, index_settings={
            "index.merge.policy.segments_per_tier": 2})
        try:
            node, engine = _engine(cluster)
            q = {"query": {"match": {"body": "alpha"}}, "size": 5}
            c.search(WRITES_INDEX, q)
            for rnd in range(3):
                for i in range(6):
                    c.index(WRITES_INDEX, "doc",
                            {"body": f"alpha w{i % 3}", "n": i},
                            id=f"m{rnd}-{i}")
                c.refresh(WRITES_INDEX)
                c.search(WRITES_INDEX, q)
            assert _wait(lambda: node.warmer.stats()["packs_done"]
                         >= node.warmer.stats()["packs_scheduled"])
            total = 40 + 18
            real_merge = engine_mod.merge_segments
            in_merge = threading.Event()

            def slow_merge(segments, gen):
                in_merge.set()
                time.sleep(1.0)
                return real_merge(segments, gen)

            monkeypatch.setattr(engine_mod, "merge_segments", slow_merge)
            old_searcher = engine.acquire_searcher()
            t = threading.Thread(target=lambda: engine.maybe_merge(
                max_merges=1))
            t.start()
            assert in_merge.wait(5)
            t0 = time.monotonic()
            r = c.search(WRITES_INDEX, q)
            waited = time.monotonic() - t0
            assert r["hits"]["total"] == total
            assert waited < 0.9, f"search waited {waited}s on merge compute"
            assert old_searcher.live_doc_count() == total  # old view intact
            t.join(15)
            monkeypatch.undo()
            assert c.search(WRITES_INDEX, q)["hits"]["total"] == total
            st = PACK_LEDGER.stats(WRITES_INDEX)
            if _wait(lambda: PACK_LEDGER.stats(WRITES_INDEX)
                     .get("compacts", 0) >= 1, timeout=6.0):
                st = PACK_LEDGER.stats(WRITES_INDEX)
                compact = [e for e in st["recent"]
                           if e["kind"] == "compact"]
                assert compact and compact[-1]["pool"] == "merge"
                assert compact[-1].get("method") == "concat"
        finally:
            cluster.close()

    def test_recovery_replays_onto_delta_aware_packs(self, tmp_path):
        """Store recovery rebuilds segments without pack hints and serves
        correctly — then fresh writes take the delta path again."""
        from elasticsearch_tpu.index.engine import Engine

        from tests.test_merge_policy import build_engine

        e, svc = build_engine(tmp_path, {})
        for rnd in range(3):
            for i in range(5):
                e.index("doc", f"{rnd}-{i}", {"body": f"alpha w{i}",
                                              "n": i})
            e.refresh()
        e.flush()
        e.translog.sync()
        e.close()
        e2 = Engine(str(tmp_path / "s"), svc, settings=Settings.EMPTY)
        e2.recover_from_store()
        e2.refresh()
        from elasticsearch_tpu.search.execute import (ShardContext,
                                                      search_shard)
        from elasticsearch_tpu.search.queries import parse_query
        from elasticsearch_tpu.search.similarity import SimilarityService

        ctx = ShardContext(e2.acquire_searcher(), svc,
                           SimilarityService(Settings.EMPTY,
                                             mapper_service=svc))
        td = search_shard(ctx, parse_query({"match": {"body": "alpha"}}), 30)
        assert td.total == 15
        # a post-recovery refresh increment carries the delta hint
        e2.index("doc", "new", {"body": "alpha", "n": 9})
        e2.refresh()
        segs = e2.acquire_searcher().segments
        assert segs[-1]._device_cache.get("pack_hint", {}).get("kind") \
            == "delta_pack"
        e2.close()

    def test_warmer_reprimes_request_cache_first_sighting_hits(
            self, tmp_path):
        """The warmer satellite: after a refresh, the shard's hot cached
        body is replayed by the warmer pool, so the FIRST post-refresh
        sighting is a request-cache hit (and it sees the new doc)."""
        cluster, c = _boot(tmp_path)
        try:
            node, engine = _engine(cluster)
            hot = {"query": {"match": {"body": "alpha"}}, "size": 0}
            assert c.search(WRITES_INDEX, hot)["hits"]["total"] == 40
            c.search(WRITES_INDEX, hot)  # hit → the body turns hot
            assert node.request_cache.has_hot(WRITES_INDEX, 0)
            c.index(WRITES_INDEX, "doc", {"body": "alpha fresh", "n": 1},
                    id="newdoc")
            c.refresh(WRITES_INDEX)
            fp = request_fingerprint(hot)

            def warmed():
                version = engine.acquire_searcher().version
                return node.request_cache.peek(
                    (WRITES_INDEX, 0, version, fp))

            assert _wait(warmed), node.warmer.stats()
            st0 = node.request_cache.stats()
            r = c.search(WRITES_INDEX, hot)
            assert r["hits"]["total"] == 41  # the warmed entry is CURRENT
            st1 = node.request_cache.stats()
            assert st1["hits"] == st0["hits"] + 1
            assert st1["misses"] == st0["misses"]
            ws = node.warmer.stats()
            assert ws["reprimes"] >= 1 and ws["queries_warmed"] >= 1
        finally:
            cluster.close()

    def test_warmer_kill_switch(self, tmp_path):
        cluster, c = _boot(tmp_path,
                           settings={"indices.warmer.enabled": "false"})
        try:
            node, engine = _engine(cluster)
            hot = {"query": {"match": {"body": "alpha"}}, "size": 0}
            c.search(WRITES_INDEX, hot)
            c.search(WRITES_INDEX, hot)
            c.index(WRITES_INDEX, "doc", {"body": "alpha", "n": 1}, id="x")
            c.refresh(WRITES_INDEX)
            # packs still warm (core serving behavior), re-prime does not
            assert _wait(lambda: node.warmer.stats()["packs_done"] >= 1)
            time.sleep(0.2)
            ws = node.warmer.stats()
            assert ws["enabled"] is False
            assert ws["reprimes"] == 0 and ws["queries_warmed"] == 0
        finally:
            cluster.close()

    def test_stats_surfaces_delta_and_compaction_rows(self, tmp_path):
        """/_nodes/stats gains the warmer section; the device section's and
        /{index}/_stats' pack rollups carry delta_packs/compacts + pools."""
        cluster, c = _boot(tmp_path, index_settings={
            "index.merge.policy.segments_per_tier": 2})
        try:
            node, engine = _engine(cluster)
            q = {"query": {"match": {"body": "alpha"}}, "size": 3}
            c.search(WRITES_INDEX, q)
            for rnd in range(3):
                c.index(WRITES_INDEX, "doc", {"body": "alpha", "n": rnd},
                        id=f"s{rnd}")
                c.refresh(WRITES_INDEX)
                c.search(WRITES_INDEX, q)
            engine.maybe_merge(max_merges=2)
            c.search(WRITES_INDEX, q)
            assert _wait(lambda: node.warmer.stats()["packs_done"]
                         >= node.warmer.stats()["packs_scheduled"])
            ns = node.client().nodes_stats()["nodes"][node.node_id]
            assert "warmer" in ns
            for key in ("packs_scheduled", "packs_done", "reprimes",
                        "queries_warmed", "enabled"):
                assert key in ns["warmer"]
            pack = ns["device"]["indices"][WRITES_INDEX]["pack"]
            for key in ("packs", "delta_packs", "remasks", "compacts",
                        "pools"):
                assert key in pack
            assert pack["delta_packs"] >= 1
            idx_stats = node.client().stats(WRITES_INDEX)
            assert idx_stats[WRITES_INDEX]["device"]["pack"][
                "delta_packs"] >= 1
        finally:
            cluster.close()


# ---------------------------------------------------------------------------
# lint: the write-path modules stay clean
# ---------------------------------------------------------------------------


def test_writes_modules_scan_clean():
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tools.tpulint import lint_paths

    paths = [os.path.join(repo, "elasticsearch_tpu", p) for p in (
        "ops/device_index.py", "ops/scoring.py", "index/engine.py",
        "index/segment.py", "index/merge_policy.py", "warmer.py",
        "indices_service.py", "search/request_cache.py", "threadpool.py",
    )]
    findings = lint_paths(paths)
    assert not findings, [f.to_dict() for f in findings]
