"""Test harness: the in-process multi-node cluster + fault injection utilities.

ref: test/TestCluster.java:88 (N real nodes in one JVM, kill/restart APIs),
test/store/MockFSDirectoryService.java:35 (random IOExceptions on store reads),
test/engine/MockInternalEngine.java:58 (suite fails on leaked searchers — here:
an acquire-tracking engine wrapper usable as an assertion context).

Usage:
    with TestCluster(n_nodes=3, data_root=tmp_path, seed=7) as cluster:
        cluster.client().create_index("idx", {"settings": {
            "number_of_shards": 4, "number_of_replicas": 1}})
        cluster.ensure_green("idx")
        cluster.kill_node(cluster.master_name())   # failover
"""

from __future__ import annotations

import contextlib
import random

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.transport.faults import FaultPolicy
from elasticsearch_tpu.transport.local import LocalTransportRegistry


class TestCluster:
    """N real nodes on one in-process transport registry (the reference boots N
    InternalNodes in one JVM — same trick, same failover surface)."""

    __test__ = False  # utility class, not a pytest collection target

    def __init__(self, n_nodes: int = 3, data_root=None, settings=None,
                 name: str = "tc", seed: int | None = None):
        self.registry = LocalTransportRegistry()
        self.n_nodes = n_nodes
        self.data_root = str(data_root) if data_root else None
        self.settings = dict(settings or {})
        self.name = name
        self.rng = random.Random(seed)
        self.nodes: dict[str, Node] = {}
        self._counter = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self, n_nodes: int | None = None):
        for _ in range(n_nodes if n_nodes is not None else self.n_nodes):
            self.add_node()
        self.nodes[next(iter(self.nodes))].wait_for_master()
        return self

    def add_node(self) -> Node:
        self._counter += 1
        nname = f"{self.name}{self._counter}"
        node = Node(name=nname, registry=self.registry,
                    settings=dict(self.settings),
                    data_path=(f"{self.data_root}/{nname}" if self.data_root
                               else None))
        node.start([node.local_node.transport_address] if not self.nodes else None)
        # block until the join's state publish lands: a client bound to this
        # node before then sees an EMPTY metadata (version 0) and raises
        # IndexMissing on perfectly healthy indices (observed as a chaos-suite
        # flake when client() picked a just-added node)
        node.wait_for_master(timeout=15.0)
        self.nodes[nname] = node
        return node

    def kill_node(self, name: str):
        """Hard-stop a node (the reference's TestCluster.stopRandomNode)."""
        node = self.nodes.pop(name)
        node.close()

    def kill_random_node(self, exclude_master: bool = False) -> str:
        names = list(self.nodes)
        if exclude_master:
            m = self.master_name()
            names = [n for n in names if n != m] or names
        victim = self.rng.choice(names)
        self.kill_node(victim)
        return victim

    def master_name(self) -> str | None:
        for name, node in self.nodes.items():
            state = node.cluster_service.state
            if state.nodes.master_id == node.local_node.id:
                return name
        return None

    def client(self):
        """A client on a random live node (the reference randomizes too)."""
        return self.nodes[self.rng.choice(list(self.nodes))].client()

    # -- fault injection (transport/faults.py) -----------------------------
    def fault_policy(self, node_name: str, seed: int | None = None) -> FaultPolicy:
        """Install (or return the already-installed) FaultPolicy on one live
        node's TransportService — the MockTransportService hook. An EXPLICIT
        seed always installs a fresh policy (replayability demands a pristine
        RNG, not one another test already advanced); without a seed, an
        existing policy is reused and a new one draws from the cluster RNG."""
        service = self.nodes[node_name].transport
        if seed is not None:
            FaultPolicy(seed).install(service)
        elif service.fault_policy is None:
            FaultPolicy(self.rng.randrange(2 ** 31)).install(service)
        return service.fault_policy

    def clear_faults(self):
        """Drop every installed fault rule on every live node."""
        for node in self.nodes.values():
            if node.transport.fault_policy is not None:
                node.transport.fault_policy.clear()

    def address(self, node_name: str) -> str:
        """A node's transport address — the `node=` pattern FaultRules match."""
        return self.nodes[node_name].local_node.transport_address

    def ensure_green(self, index=None, timeout: float = 30.0):
        h = self.client().cluster_health(index, wait_for_status="green",
                                         timeout=timeout)
        assert h["status"] == "green", h
        return h

    def close(self):
        for node in list(self.nodes.values()):
            with contextlib.suppress(Exception):
                node.close()
        self.nodes.clear()

    def __enter__(self):
        if not self.nodes:
            self.start()
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------


class FaultyStore:
    """Wraps a shard's Store so reads fail with IOError at a given rate —
    MockFSDirectoryService's random-IOException wrapper, shrunk to the read path
    that peer recovery and gateway restore exercise."""

    def __init__(self, inner, fail_rate: float = 0.3, seed: int = 0):
        self._inner = inner
        self._rng = random.Random(seed)
        self.fail_rate = fail_rate
        self.reads = 0
        self.failures = 0

    def read_segment(self, gen, verify=None):
        self.reads += 1
        if self._rng.random() < self.fail_rate:
            self.failures += 1
            raise IOError(f"injected read failure (segment {gen})")
        return self._inner.read_segment(gen, verify)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class SearcherLeakTracker:
    """Counts engine searcher acquisitions inside a scope — MockInternalEngine's
    INFLIGHT_ENGINE_SEARCHERS check. Searchers here are snapshot objects released
    by GC, so the assertable invariant is acquisition-count sanity (no unbounded
    growth per request), not explicit release."""

    def __init__(self, engine):
        self.engine = engine
        self.acquired = 0
        self._orig = None

    def __enter__(self):
        orig = self.engine.acquire_searcher
        self._orig = orig

        def tracked():
            self.acquired += 1
            return orig()

        self.engine.acquire_searcher = tracked
        return self

    def __exit__(self, *exc):
        self.engine.acquire_searcher = self._orig


