"""Runtime collective-trace sanitizer (common/meshtrace.py).

The dynamic twin of the tpulint SPMD family (TPU014-TPU016): under
ESTPU_MESHTRACE=1 every shard_map trace records its collective launch
sequence per program, and the conftest session gate replays each program and
fails on any cross-trace divergence — the single-process rehearsal of the
multi-host SPMD deadlock (every process must enqueue the identical collective
sequence or the mesh hangs on hardware with no error). Covered here:

- the recorder costs exactly ZERO when the env knob is off (jax.lax
  collectives and shard_map are the pristine functions, no wrapper anywhere);
- a program whose trace branches on host-divergent state (the seeded
  ESTPU_FAKE_HOST env read below — exactly what TPU014 flags statically)
  fails the gate with a report naming the first differing collective site in
  BOTH traces;
- a divergence-free program traced repeatedly (and replayed) stays clean;
- a warmed mesh-serving loop (build_sharded_index + MeshSearchExecutor over
  a 2-shard device mesh) records real collective traffic with no sequence
  mismatch and 0 recompiles under the hard transfer guard, and the replay
  leg re-traces it cleanly.

Subprocesses are used wherever the tracer must be armed: installing it
patches jax.lax/shard_map process-wide, which must never leak into the rest
of the suite.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SELF = os.path.abspath(__file__)


def _site_line(tag: str) -> int:
    with open(SELF, encoding="utf-8") as f:
        for i, ln in enumerate(f.read().splitlines(), 1):
            if f"# {tag}" in ln:
                return i
    raise AssertionError(f"no line marked # {tag}")


def _run(mode, env_extra=None, timeout=300):
    env = {**os.environ}
    env.pop("ESTPU_MESHTRACE", None)
    env.update(env_extra or {})
    return subprocess.run([sys.executable, "-m", "tests.test_meshtrace", mode],
                          capture_output=True, text=True, cwd=REPO,
                          timeout=timeout, env=env)


# ---------------------------------------------------------------------------
# env knob off: zero overhead, nothing patched
# ---------------------------------------------------------------------------


def test_overhead_zero_when_knob_off():
    """Importing meshtrace must patch NOTHING by itself; with the knob unset,
    maybe_install is a no-op and jax.lax / shard_map stay pristine. (When the
    suite itself runs under ESTPU_MESHTRACE=1 — the CI mesh leg — the tracer
    is armed instead and the session gate replays + checks the programs.)"""
    import jax

    from elasticsearch_tpu.common import meshtrace

    if os.environ.get("ESTPU_MESHTRACE", "") in ("1", "on", "true"):
        assert meshtrace.TRACER.enabled
        assert getattr(jax.lax.psum, "_estpu_meshtrace", False)
        return
    assert meshtrace.maybe_install() is None
    assert not meshtrace.TRACER.enabled
    for name in meshtrace.COLLECTIVES:
        fn = getattr(jax.lax, name, None)
        assert fn is None or not getattr(fn, "_estpu_meshtrace", False), name
    from jax.experimental import shard_map as sm_mod

    assert not getattr(sm_mod.shard_map, "_estpu_meshtrace", False)
    if getattr(jax, "shard_map", None) is not None:
        assert not getattr(jax.shard_map, "_estpu_meshtrace", False)


# ---------------------------------------------------------------------------
# the divergent program under the tracer
# ---------------------------------------------------------------------------


def test_divergent_traces_fail_naming_both_sites():
    """The driver traces ONE program twice with different ESTPU_FAKE_HOST
    values — the single-process stand-in for two fleet processes tracing the
    same program. The branch steers the collective order, so the gate must
    fail with a CollectiveTraceMismatch naming the first differing collective
    site of BOTH traces by file:line."""
    res = _run("divergent", {"ESTPU_MESHTRACE": "1"})
    assert res.returncode != 0, res.stdout + res.stderr
    assert "CollectiveTraceMismatch" in res.stderr
    assert "diverge" in res.stderr
    for tag in ("SITE_A", "SITE_B"):
        line_no = _site_line(tag)
        assert f"test_meshtrace.py:{line_no}" in res.stderr, \
            (tag, line_no, res.stderr)


def test_divergence_free_traces_pass_and_replay_clean():
    res = _run("uniform", {"ESTPU_MESHTRACE": "1"})
    assert res.returncode == 0, res.stdout + res.stderr
    snap = json.loads(res.stdout.splitlines()[-1])
    assert snap["programs"] == 1
    assert snap["launches"] >= 3  # two traces + at least one replay
    assert snap["replayed"] >= 1
    assert snap["replay_errors"] == 0
    assert snap["mismatches"] == 0


def test_driver_runs_clean_without_the_knob():
    res = _run("uniform")
    assert res.returncode == 0, res.stdout + res.stderr
    snap = json.loads(res.stdout.splitlines()[-1])
    assert snap == {}  # tracer off: nothing recorded, nothing patched


# ---------------------------------------------------------------------------
# warmed mesh serving: real collective traffic, no mismatch, 0 recompiles
# ---------------------------------------------------------------------------


def test_warmed_mesh_serving_records_clean_sequences():
    """The real SPMD serving path (2-shard mesh, DFS psum + all_gather top-k)
    with the tracer armed: the warmed loop must run with 0 recompiles under
    the hard transfer guard, record real collective launches, show ZERO
    sequence mismatches, and replay cleanly at the end — the invariant the
    ESTPU_MESHTRACE=1 CI leg holds over the whole mesh subset."""
    res = _run("serving", {"ESTPU_MESHTRACE": "1"}, timeout=420)
    assert res.returncode == 0, res.stdout + res.stderr
    snap = json.loads(res.stdout.splitlines()[-1])
    assert snap["launches"] > 0
    assert snap["collectives"] > 0
    assert snap["mismatches"] == 0, snap
    assert snap["replayed"] > 0
    assert snap["replay_errors"] == 0, snap


# ---------------------------------------------------------------------------
# subprocess drivers
# ---------------------------------------------------------------------------


def _mesh_and_relax():
    import inspect

    import jax
    import numpy as np
    from jax.sharding import Mesh

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    mesh = Mesh(np.array(jax.devices()), ("d",))
    params = inspect.signature(shard_map).parameters
    relax = {"check_vma": False} if "check_vma" in params \
        else {"check_rep": False}
    return shard_map, mesh, relax


def _divergent_program(x):
    import jax

    if os.environ.get("ESTPU_FAKE_HOST") == "0":
        s = jax.lax.psum(x, "d")  # SITE_A
        return jax.lax.all_gather(s, "d")
    g = jax.lax.all_gather(x, "d")  # SITE_B
    return jax.lax.psum(g, "d")


def _uniform_program(x):
    import jax

    s = jax.lax.psum(x, "d")
    return jax.lax.all_gather(s, "d")


def _trace_twice(program, fake_hosts) -> None:
    """Trace `program` once per entry in fake_hosts (fresh shard_map wrapper
    each time — two processes never share a trace cache), then replay and
    run the gate exactly like the conftest session fixture."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from elasticsearch_tpu.common import meshtrace

    shard_map, mesh, relax = _mesh_and_relax()
    for host in fake_hosts:
        os.environ["ESTPU_FAKE_HOST"] = host
        f = shard_map(program, mesh=mesh, in_specs=(P("d"),),
                      out_specs=P(None, "d"), **relax)
        jax.eval_shape(f, jax.ShapeDtypeStruct((len(mesh.devices), 2),
                                               jnp.float32))
    if meshtrace.TRACER.enabled:
        meshtrace.TRACER.replay_all()
        meshtrace.TRACER.check()
    print(json.dumps(meshtrace.TRACER.snapshot()
                     if meshtrace.TRACER.enabled else {}))


def _serving_driver() -> None:
    import tempfile

    import numpy as np

    from elasticsearch_tpu.common import meshtrace
    from elasticsearch_tpu.common.jaxenv import sanitize
    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.index import Engine
    from elasticsearch_tpu.mapper import MapperService
    from elasticsearch_tpu.search import ShardContext, parse_query
    from elasticsearch_tpu.search.execute import lower_flat
    from elasticsearch_tpu.search.similarity import SimilarityService

    assert meshtrace.TRACER.enabled, "driver requires ESTPU_MESHTRACE=1"

    import jax
    from jax.sharding import Mesh

    from elasticsearch_tpu.parallel.mesh_search import (
        MeshSearchExecutor,
        build_sharded_index,
    )

    words = ["quick", "brown", "fox", "lazy", "dog", "summer", "red", "bear"]
    settings = Settings.from_flat({})
    svc = MapperService(settings)
    with tempfile.TemporaryDirectory() as td:
        searchers = []
        engines = []
        for si in range(2):
            e = Engine(os.path.join(td, f"shard{si}"), svc)
            for i in range(24):
                e.index("doc", str(i), {
                    "body": f"{words[(si + i) % 8]} {words[(si + i + 3) % 8]}"})
            e.refresh()
            engines.append(e)
            searchers.append(e.acquire_searcher())
        try:
            mesh = Mesh(np.array(jax.devices()[:2]), ("shards",))
            sidx = build_sharded_index(searchers, fields=["body"], mesh=mesh)
            ex = MeshSearchExecutor(sidx, mesh, similarity="BM25")
            ctx = ShardContext(searchers[0], svc,
                               SimilarityService(settings, mapper_service=svc))
            plan = lower_flat(parse_query({"match": {"body": "quick brown"}}),
                              ctx)
            warm = ex.search([plan], k=5)  # first run compiles + traces freely
            with sanitize(max_compiles=0, transfers="disallow") as rep:
                for _ in range(3):
                    again = ex.search([plan], k=5)  # the warmed serving loop
            assert rep.compiles == 0, rep.compile_events
            assert rep.mesh is not None and rep.mesh["mismatches"] == 0, rep.mesh
            np.testing.assert_array_equal(again.doc, warm.doc)
        finally:
            for e in engines:
                e.close()

    meshtrace.TRACER.replay_all()
    meshtrace.TRACER.check()  # any sequence divergence fails the driver
    snap = meshtrace.TRACER.snapshot()
    assert snap["launches"] > 0 and snap["collectives"] > 0, snap
    print(json.dumps(snap))


def _main(mode: str) -> int:
    from elasticsearch_tpu.common.jaxenv import force_cpu_platform

    force_cpu_platform(n_devices=4 if mode != "serving" else 2)

    from elasticsearch_tpu.common import meshtrace

    meshtrace.maybe_install()
    if mode == "divergent":
        _trace_twice(_divergent_program, ("0", "1"))
    elif mode == "uniform":
        _trace_twice(_uniform_program, ("0", "0"))
    elif mode == "serving":
        _serving_driver()
    else:
        raise SystemExit(f"unknown mode {mode!r}")
    return 0


if __name__ == "__main__":
    sys.exit(_main(sys.argv[1]))
