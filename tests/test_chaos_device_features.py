"""Chaos testing of the device-served search features on a real multi-node
cluster: function_score, fused aggregations and field sorts must return
identical answers before and after a node kill + replica promotion, and the
device serving paths must actually be the ones answering.

ref: the reference's failover suites run real searches against TestCluster
across node kills (src/test/java/org/elasticsearch/recovery/, discovery/);
here the searches additionally pin the TPU-native serving kernels.
"""

from __future__ import annotations

import math

import pytest

from tests.harness import TestCluster


def _index_docs(client, n=90):
    for i in range(n):
        client.index("shop", "item", {
            "body": ("red shiny " if i % 2 else "blue matte ") + f"thing{i % 7}",
            "price": float(i % 50 + 1), "pop": i % 30 + 1,
        }, id=str(i))
    client.refresh("shop")


def _searches():
    return [
        {"query": {"function_score": {
            "query": {"match": {"body": "red shiny"}},
            "script_score": {"script": "_score * log(2 + doc['pop'].value)"}}},
         "size": 10},
        {"query": {"filtered": {"query": {"match": {"body": "blue"}},
                                "filter": {"range": {"price": {"gte": 20}}}}},
         "size": 0,
         "aggs": {"p": {"stats": {"field": "price"}},
                  "by_pop": {"terms": {"field": "pop", "size": 40}}}},
        {"query": {"match": {"body": "thing3"}},
         "sort": [{"price": "desc"}], "size": 10},
    ]


def _snapshot(client, bodies):
    out = []
    for b in bodies:
        r = client.search("shop", b)
        hits = [(h["_id"], round(h.get("_score") or 0.0, 5),
                 tuple(h.get("sort", []))) for h in r["hits"]["hits"]]
        aggs = r.get("aggregations")
        out.append((r["hits"]["total"], hits, aggs))
    return out


def _approx_equal(a, b):
    if isinstance(a, dict) and isinstance(b, dict):
        return set(a) == set(b) and all(_approx_equal(a[k], b[k]) for k in a)
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(_approx_equal(x, y) for x, y in zip(a, b))
    if isinstance(a, float) and isinstance(b, float):
        return (math.isnan(a) and math.isnan(b)) or a == pytest.approx(b, rel=1e-5)
    return a == b


def test_device_features_survive_failover(tmp_path):
    from elasticsearch_tpu.search.service import SERVING_COUNTERS

    with TestCluster(n_nodes=3, data_root=tmp_path, seed=11) as cluster:
        client = cluster.client()
        client.create_index("shop", {"settings": {
            "number_of_shards": 3, "number_of_replicas": 1}})
        cluster.ensure_green("shop")
        _index_docs(client)

        bodies = _searches()
        before_counts = {k: SERVING_COUNTERS[k] for k in
                         ("device_function_score", "device_aggs", "device_sort")}
        baseline = _snapshot(client, bodies)
        # every search was served by its device path on every queried shard
        for key in before_counts:
            assert SERVING_COUNTERS[key] > before_counts[key], key

        victim = cluster.kill_random_node(exclude_master=True)
        cluster.ensure_green("shop")

        after = _snapshot(client, bodies)
        for b, x, y in zip(bodies, baseline, after):
            assert _approx_equal(x, y), (victim, b, x, y)


def test_fetch_failure_drops_shard_not_search(tmp_path):
    # a shard lost between query and fetch: its hits drop, the rest return,
    # and a failure is recorded (ref: ShardFetchFailure semantics)
    with TestCluster(n_nodes=1, data_root=tmp_path, seed=3) as cluster:
        client = cluster.client()
        client.create_index("f", {"settings": {
            "number_of_shards": 2, "number_of_replicas": 0}})
        cluster.ensure_green("f")
        for i in range(40):
            client.index("f", "d", {"body": "common words here"}, id=str(i))
        client.refresh("f")
        import elasticsearch_tpu.actions as actions_mod

        orig = actions_mod.execute_fetch_phase
        state = {"failed": False}

        def flaky(ctx, req, docs, index_name="index", shard_id=0):
            if shard_id == 1 and not state["failed"]:
                state["failed"] = True
                raise RuntimeError("node lost between phases")
            return orig(ctx, req, docs, index_name=index_name,
                        shard_id=shard_id)

        actions_mod.execute_fetch_phase = flaky
        try:
            r = client.search("f", {"query": {"match": {"body": "common"}},
                                    "size": 40})
        finally:
            actions_mod.execute_fetch_phase = orig
        assert state["failed"]
        assert r["_shards"]["failed"] >= 1
        assert 0 < len(r["hits"]["hits"]) < 40  # shard 0's hits survived
        assert r["hits"]["total"] == 40
